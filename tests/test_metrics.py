"""Continuous-telemetry subsystem (metrics/ + tools/history, ISSUE 5).

Covers the registry core (disabled path is a no-op with a tested
overhead bound, no sampler thread when off), Prometheus exposition
(label escaping, histogram invariants), the 3-worker distributed
snapshot merge, the rotating event log (+ crash-truncated tail
tolerated by tools/history, deterministic regression diff), EXPLAIN
ANALYZE golden output, and the stale last_query_metrics fix."""
import json
import os
import re
import threading

import numpy as np
import pyarrow as pa
import pytest

from harness import tpu_session
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.metrics import (MetricRegistry, SAMPLER_THREAD_NAME,
                                      active_registry, install_metrics,
                                      merge_snapshots, metric_inventory,
                                      prometheus_text, registry_snapshot,
                                      sampler_thread, shutdown_metrics)

pytestmark = pytest.mark.filterwarnings("ignore::ResourceWarning")

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def _small_table(n=2000, k=7):
    return pa.table({"k": pa.array(np.arange(n) % k),
                     "v": pa.array(np.arange(n, dtype=np.float64))})


# ---------------------------------------------------------------------------
# disabled path
# ---------------------------------------------------------------------------

def test_disabled_no_registry_no_sampler_thread():
    """With metrics off (the default) a query battery installs no
    registry and starts no sampler thread."""
    assert active_registry() is None
    s = tpu_session()
    df = (s.create_dataframe(_small_table()).group_by("k")
          .agg(F.sum(F.col("v")).with_name("sv")))
    assert df.collect_arrow().num_rows == 7
    assert df.filter(F.col("k") > 2).count() > 0
    assert active_registry() is None
    assert sampler_thread() is None
    assert not [t for t in threading.enumerate()
                if t.name == SAMPLER_THREAD_NAME]


def test_disabled_overhead_is_one_branch():
    """The instrumented-site pattern when disabled is a module-global
    load + branch (same bound style as the tracer's)."""
    import time
    from spark_rapids_tpu.metrics import registry as metrics_registry
    assert metrics_registry.REGISTRY is None
    n = 200_000

    def site_loop():
        acc = 0
        for _ in range(n):
            mr = metrics_registry.REGISTRY   # the instrumented pattern
            if mr is not None:
                mr.counter("srtpu_oom_retries_total").inc()  # pragma: no cover
            acc += 1
        return acc

    def bare_loop():
        acc = 0
        for _ in range(n):
            acc += 1
        return acc

    t0 = time.perf_counter(); site_loop(); site = time.perf_counter() - t0
    t0 = time.perf_counter(); bare_loop(); bare = time.perf_counter() - t0
    assert site < max(10 * bare, bare + 0.5), (site, bare)


def test_undeclared_metric_rejected():
    reg = MetricRegistry()
    with pytest.raises(KeyError):
        reg.counter("srtpu_not_in_the_inventory_total")
    with pytest.raises(TypeError):
        reg.gauge("srtpu_oom_retries_total")   # declared as a counter


# ---------------------------------------------------------------------------
# exposition
# ---------------------------------------------------------------------------

def test_prometheus_exposition_parses():
    reg = MetricRegistry()
    reg.counter("srtpu_queries_total", status="ok").inc(3)
    reg.counter("srtpu_queries_total", status='fa"il\\ed\n').inc()
    reg.gauge("srtpu_hbm_used_bytes").set(12345)
    h = reg.histogram("srtpu_query_seconds", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    txt = prometheus_text(reg.snapshot())
    lines = txt.splitlines()
    # HELP/TYPE headers present and typed correctly
    assert "# TYPE srtpu_queries_total counter" in lines
    assert "# TYPE srtpu_query_seconds histogram" in lines
    # label escaping: backslash, quote, newline
    esc = [l for l in lines if "fa\\\"il\\\\ed\\n" in l]
    assert esc, txt
    # histogram invariants: cumulative buckets, +Inf == count,
    # sum matches the observations
    def val(sub):
        return [float(l.rsplit(" ", 1)[1]) for l in lines
                if l.startswith(sub)]
    buckets = val("srtpu_query_seconds_bucket")
    assert buckets == sorted(buckets)          # cumulative
    assert buckets == [1.0, 3.0, 4.0, 5.0]     # le=.1,1,10,+Inf
    (count,) = val("srtpu_query_seconds_count")
    assert count == 5.0 == buckets[-1]
    (total,) = val("srtpu_query_seconds_sum")
    assert abs(total - 56.05) < 1e-9
    # every sample line parses as "name{labels} value"
    for l in lines:
        if l.startswith("#") or not l:
            continue
        assert re.match(r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? \S+$", l), l


def test_snapshot_merge_stamps_worker_label():
    a, b = MetricRegistry(), MetricRegistry()
    a.counter("srtpu_oom_retries_total").inc(2)
    b.counter("srtpu_oom_retries_total").inc(5)
    merged = merge_snapshots({"worker-0": a.snapshot(),
                              "worker-1": b.snapshot()})
    series = merged["srtpu_oom_retries_total"]["series"]
    got = {s["labels"]["worker"]: s["value"] for s in series}
    assert got == {"worker-0": 2, "worker-1": 5}


def test_summary_exposition_quantiles_and_exemplar_parse():
    """The Summary kind renders as Prometheus quantile series plus
    _sum/_count, and an attached exemplar survives the wire in
    OpenMetrics syntax (`` # {labels} value ts``)."""
    reg = MetricRegistry()
    sm = reg.summary("srtpu_query_latency_seconds", tenant="a")
    for i in range(1, 101):
        sm.observe(i / 100.0)
    snap = reg.snapshot()
    # decorate the series the way ops/slo.decorate_snapshot does
    snap["srtpu_query_latency_seconds"]["series"][0]["exemplar"] = {
        "labels": {"trace_path": "/tmp/trace.json", "query_id": "7"},
        "value": 1.0, "ts": 1700000000.0}
    txt = prometheus_text(snap)
    lines = txt.splitlines()
    assert "# TYPE srtpu_query_latency_seconds summary" in lines
    for q in ("0.5", "0.95", "0.99"):
        ql = [l for l in lines
              if f'quantile="{q}"' in l and 'tenant="a"' in l]
        assert ql, (q, txt)
    p99 = [l for l in lines if 'quantile="0.99"' in l]
    assert abs(float(p99[0].rsplit(" ", 1)[1]) - 0.99) < 0.05
    count = [l for l in lines
             if l.startswith("srtpu_query_latency_seconds_count")]
    assert count and " # {" in count[0]
    m = re.match(r'^(\S+)\{(.*)\} (\S+) # \{(.*)\} (\S+) (\S+)$',
                 count[0])
    assert m, count[0]
    assert 'trace_path="/tmp/trace.json"' in m.group(4)
    assert float(m.group(3)) == 100.0


def test_summary_merge_is_deterministic_and_exact():
    """Three shard registries merged through merge_snapshots fold to
    EXACTLY the single-process sketch — bucket counts are integers, so
    distribution across workers cannot drift the quantiles."""
    from spark_rapids_tpu.metrics import QuantileSketch, fold_sketches
    rng = np.random.RandomState(5)
    vals = [float(v) for v in rng.lognormal(0.0, 1.0, 3000)]
    whole = MetricRegistry()
    shards = [MetricRegistry() for _ in range(3)]
    for i, v in enumerate(vals):
        whole.summary("srtpu_query_latency_seconds",
                      tenant="a").observe(v)
        shards[i % 3].summary("srtpu_query_latency_seconds",
                              tenant="a").observe(v)
    merged = merge_snapshots({f"worker-{i}": r.snapshot()
                              for i, r in enumerate(shards)})
    series = merged["srtpu_query_latency_seconds"]["series"]
    assert [s["labels"]["worker"] for s in series] == \
        ["worker-0", "worker-1", "worker-2"]
    folded = fold_sketches([s["sketch"] for s in series])
    want = QuantileSketch.from_json(
        whole.snapshot()["srtpu_query_latency_seconds"]
        ["series"][0]["sketch"])
    # integer bucket counts: the shard split cannot drift a quantile
    assert folded.bins == want.bins and folded.count == want.count
    assert folded.quantiles((0.5, 0.95, 0.99)) == \
        want.quantiles((0.5, 0.95, 0.99))


def test_per_metric_buckets_and_600s_ceiling():
    """srtpu_query_seconds carries its own inventory buckets topping at
    600s (the 60s default ceiling saturated on long queries), while
    explicit buckets= still win over the inventory."""
    from spark_rapids_tpu.metrics import metric_inventory
    reg = MetricRegistry()
    h = reg.histogram("srtpu_query_seconds", tenant="a")
    assert h.buckets[-1] == 600.0
    h.observe(300.0)                       # lands in a real bucket now
    snap = reg.snapshot()
    buckets = dict(snap["srtpu_query_seconds"]["series"][0]["buckets"])
    assert buckets[300.0] == 1 and buckets[120.0] == 0
    assert metric_inventory()["srtpu_query_seconds"]["buckets"][-1] \
        == 600.0
    # explicit buckets still beat the inventory (the PR-5 contract)
    reg2 = MetricRegistry()
    h2 = reg2.histogram("srtpu_query_seconds", buckets=(1.0, 2.0))
    assert h2.buckets == (1.0, 2.0)


def test_bounded_label_caps_cardinality():
    reg = MetricRegistry()
    seen = {reg.bounded_label("srtpu_digest_latency_seconds", "digest",
                              f"d{i}", cap=4) for i in range(10)}
    assert seen == {"d0", "d1", "d2", "d3", "other"}
    # identity is sticky for values admitted before the cap
    assert reg.bounded_label("srtpu_digest_latency_seconds", "digest",
                             "d2", cap=4) == "d2"


def test_registry_snapshot_samples_runtime_gauges():
    """One synchronous sample pass populates the hbm/spill/semaphore/
    shuffle gauges even with the sampler thread off."""
    reg = MetricRegistry()
    snap = registry_snapshot(reg)
    for name in ("srtpu_hbm_used_bytes", "srtpu_hbm_budget_bytes",
                 "srtpu_spill_store_host_bytes",
                 "srtpu_semaphore_queue_depth",
                 "srtpu_shuffle_block_store_bytes"):
        assert name in snap, name


# ---------------------------------------------------------------------------
# enabled single-process path
# ---------------------------------------------------------------------------

def test_enabled_query_counters_and_sampler():
    s = tpu_session({"spark.rapids.tpu.metrics.enabled": True,
                     "spark.rapids.tpu.metrics.sample.intervalMs": 50})
    df = (s.create_dataframe(_small_table()).group_by("k")
          .agg(F.sum(F.col("v")).with_name("sv")))
    assert df.collect_arrow().num_rows == 7
    reg = active_registry()
    assert reg is not None
    assert sampler_thread() is not None
    snap = registry_snapshot(reg)
    ok = [se for se in snap["srtpu_queries_total"]["series"]
          if se["labels"].get("status") == "ok"]
    assert ok and ok[0]["value"] >= 1
    hist = snap["srtpu_query_seconds"]["series"][0]
    assert hist["count"] >= 1
    assert snap["srtpu_hbm_budget_bytes"]["series"][0]["value"] > 0
    shutdown_metrics()
    assert sampler_thread() is None
    assert active_registry() is None


# ---------------------------------------------------------------------------
# distributed: 3 workers, merged snapshot
# ---------------------------------------------------------------------------

def test_three_worker_snapshot_merge(tmp_path):
    from spark_rapids_tpu.config import TpuConf
    from spark_rapids_tpu.shuffle.cluster import LocalCluster
    conf = TpuConf({"spark.rapids.tpu.metrics.enabled": True,
                    "spark.rapids.tpu.metrics.sample.intervalMs": 100})
    cl = LocalCluster(3, conf=conf)
    elog_dir = str(tmp_path / "elog")
    try:
        rng = np.random.RandomState(7)
        t = pa.table({"k": pa.array(rng.randint(0, 13, 9000)),
                      "v": pa.array(rng.uniform(0, 100, 9000))})
        s = tpu_session({"spark.rapids.tpu.eventLog.enabled": True,
                         "spark.rapids.tpu.eventLog.dir": elog_dir})
        df = (s.create_dataframe(t).group_by("k")
              .agg(F.sum(F.col("v")).with_name("sv"),
                   F.count_star().with_name("n")))
        got = cl.execute(df).to_pandas().sort_values("k") \
                .reset_index(drop=True)
        # fault_stats surfaced on the session by the cluster run (the
        # oracle collect below clears it again, by design)
        assert isinstance(s.last_fault_stats, dict)
        want = df.collect_arrow().to_pandas().sort_values("k") \
                 .reset_index(drop=True)
        np.testing.assert_allclose(got["sv"], want["sv"], rtol=1e-9)
        assert s.last_fault_stats is None   # driver-local query cleared it
        view = cl.metrics_snapshot()
        lanes = set(view["workers"])
        assert {"worker-0", "worker-1", "worker-2"} <= lanes, lanes
        assert "driver" in lanes
        # the cluster run appended a durable clusterQuery record
        from spark_rapids_tpu.tools.history import load_events
        events, _ = load_events(elog_dir)
        cq = [e for e in events if e.get("event") == "clusterQuery"]
        assert cq and "workers_lost" in cq[0]["faultStats"]
        txt = cl.prometheus_snapshot()
        for series in ("srtpu_hbm_used_bytes",
                       "srtpu_spill_store_host_bytes",
                       "srtpu_semaphore_queue_depth",
                       "srtpu_shuffle_block_store_bytes"):
            for w in ("worker-0", "worker-1", "worker-2"):
                pat = re.compile(
                    rf'^{series}\{{[^}}]*worker="{w}"[^}}]*\}} ',
                    re.M)
                assert pat.search(txt), (series, w)
        # workers actually shuffled: put bytes recorded somewhere
        put = [se["value"] for se in
               view["aggregate"]["srtpu_shuffle_put_bytes_total"]["series"]
               if se["labels"]["worker"].startswith("worker-")]
        assert sum(put) > 0
        # worker task-wall summaries (ISSUE 20): every worker lane
        # ships a serialized quantile sketch that survives the merge
        # and renders as quantile series in the merged exposition
        task_ent = view["aggregate"]["srtpu_worker_task_seconds"]
        assert task_ent["kind"] == "summary"
        task_series = [se for se in task_ent["series"]
                       if se["labels"]["worker"].startswith("worker-")]
        assert task_series, "no worker task summaries merged"
        assert all(se["count"] >= 1 and se["sketch"]["bins"]
                   for se in task_series)
        q_pat = re.compile(
            r'^srtpu_worker_task_seconds\{[^}]*quantile="0\.99"'
            r'[^}]*worker="worker-\d+"[^}]*\} ', re.M)
        assert q_pat.search(txt), "no worker p99 line in exposition"
    finally:
        cl.shutdown()
        shutdown_metrics()


# ---------------------------------------------------------------------------
# event log + history
# ---------------------------------------------------------------------------

def _run_queries(s, n):
    t = _small_table()
    for i in range(n):
        df = (s.create_dataframe(t).filter(F.col("v") > float(i))
              .group_by("k").agg(F.sum(F.col("v")).with_name("sv")))
        assert df.collect_arrow().num_rows == 7


def test_event_log_rotation_and_truncated_tail(tmp_path):
    from spark_rapids_tpu.tools.history import (build_history,
                                                load_events)
    d = str(tmp_path / "elog")
    s = tpu_session({"spark.rapids.tpu.eventLog.enabled": True,
                     "spark.rapids.tpu.eventLog.dir": d,
                     "spark.rapids.tpu.eventLog.rotate.maxBytes": 2048})
    _run_queries(s, 4)
    files = sorted(os.listdir(d))
    assert any(f.startswith("events-") for f in files), files
    # crash-truncate the active file's tail (created if the final write
    # rotated it away — a crash can land at any point in the cycle)
    with open(os.path.join(d, "events.jsonl"), "a") as f:
        f.write('{"event": "queryStart", "queryId": 99, "trunc')
    events, skipped = load_events(d)
    assert skipped == 1
    history = build_history(events)
    ok = [q for q in history if q["status"] == "ok"]
    assert len(ok) == 4
    # the queryEnd schema fields are present
    assert all(q["durationMs"] is not None for q in ok)
    assert all(q["planDigest"] for q in ok)
    assert ok[0]["metrics"] is not None
    assert "maxDeviceBytes" in ok[0]["metrics"]


def test_history_cli_and_diff(tmp_path, capsys):
    from spark_rapids_tpu.tools.history import main
    base, new = str(tmp_path / "a"), str(tmp_path / "b")
    for d, n in ((base, 2), (new, 3)):
        s = tpu_session({"spark.rapids.tpu.eventLog.enabled": True,
                         "spark.rapids.tpu.eventLog.dir": d})
        _run_queries(s, n)
    assert main([base]) == 0
    out = capsys.readouterr().out
    assert "== Query history" in out and "2 ok" in out
    assert main([new, "--slowest", "2"]) == 0
    assert "== Slowest 2 queries" in capsys.readouterr().out
    # diff is deterministic: same invocation twice, identical bytes
    assert main([base, "--diff", new]) == 0
    d1 = capsys.readouterr().out
    assert main([base, "--diff", new]) == 0
    d2 = capsys.readouterr().out
    assert d1 == d2
    assert "== Regression diff" in d1
    # every digest in both logs appears
    assert main([base, "--diff", new, "--json"]) == 0
    rows = json.loads(capsys.readouterr().out)
    matched = [r for r in rows if r.get("digest")]
    assert matched, rows
    assert all(r["baseMs"] > 0 and r["newMs"] > 0 for r in matched)


def test_failed_query_does_not_leave_stale_metrics():
    """Satellite fix: a query that raises BEFORE execution must not
    leave the previous run's last_query_metrics behind."""
    s = tpu_session()
    df = (s.create_dataframe(_small_table()).group_by("k")
          .agg(F.sum(F.col("v")).with_name("sv")))
    # a later driver-local query must not inherit a cluster run's
    # fault stats either (same staleness class)
    s.last_fault_stats = {"workers_lost": 1}
    assert df.collect_arrow().num_rows == 7
    assert s.last_query_metrics is not None
    assert s.last_fault_stats is None
    s.set_conf("spark.rapids.tpu.sql.mode", "explainOnly")
    with pytest.raises(RuntimeError):
        df.collect_arrow()
    assert s.last_query_metrics is None


# ---------------------------------------------------------------------------
# EXPLAIN ANALYZE
# ---------------------------------------------------------------------------

def test_explain_analyze_golden(capsys):
    s = tpu_session()
    t = _small_table()
    df = (s.create_dataframe(t).filter(F.col("v") > 100.0)
          .with_column("w", F.col("v") * F.lit(2.0))
          .group_by("k").agg(F.sum(F.col("w")).with_name("sw"))
          .order_by("k").limit(5))
    out = df.explain("analyze")
    capsys.readouterr()                      # swallow the print
    norm = re.sub(r"\d+(?:\.\d+)?ms", "_ms", out)
    with open(os.path.join(FIXTURES, "explain_analyze_golden.txt")) as f:
        assert norm == f.read()
    # analyze EXECUTED the query: metrics from the run are live
    assert s.last_query_metrics is not None


def test_explain_analyze_self_time_bounds():
    s = tpu_session()
    df = (s.create_dataframe(_small_table()).group_by("k")
          .agg(F.sum(F.col("v")).with_name("sv")))
    out = df._explain_analyze()
    times = [float(m) for m in re.findall(r"time=(\d+\.\d+)ms", out)]
    selfs = [float(m) for m in re.findall(r"self=(\d+\.\d+)ms", out)]
    assert len(times) == len(selfs) >= 2
    assert all(sf <= tm + 1e-9 for tm, sf in zip(times, selfs))
    # root cumulative bounds every operator's self time sum-ish: the
    # root's time is the largest (children are pulled through it)
    assert times[0] == max(times)


# ---------------------------------------------------------------------------
# catalog / docs coherence
# ---------------------------------------------------------------------------

def test_inventory_covers_history_key_metrics():
    from spark_rapids_tpu.tools.history import KEY_METRICS
    inv = set(metric_inventory())
    missing = [n for n in KEY_METRICS if n not in inv]
    assert not missing, missing


def test_metrics_file_summary(tmp_path, capsys):
    from spark_rapids_tpu.tools.history import main
    reg = MetricRegistry()
    reg.counter("srtpu_oom_retries_total").inc(3)
    snap = registry_snapshot(reg)
    p = str(tmp_path / "m.json")
    with open(p, "w") as f:
        json.dump({"rung": "x", "snapshot": snap}, f, default=float)
    assert main(["--metrics-file", p]) == 0
    out = capsys.readouterr().out
    assert "srtpu_oom_retries_total 3" in out
    assert "srtpu_hbm_used_bytes" in out


def test_install_metrics_roundtrip():
    reg = MetricRegistry()
    assert install_metrics(reg) is reg
    assert active_registry() is reg
    install_metrics(None)
    assert active_registry() is None
