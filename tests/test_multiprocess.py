"""Multi-process shuffle runtime (shuffle/cluster.py + transport.py):
worker processes discovered via heartbeats, shuffle blocks moving over
TCP, differential against the local engine (the reference's
local-cluster tier, SURVEY.md section 4.3; RapidsShuffleInternalManagerBase
threaded writer/reader analog)."""
import numpy as np
import pyarrow as pa
import pytest

from harness import tpu_session
from spark_rapids_tpu.api import functions as F

pytestmark = pytest.mark.filterwarnings("ignore::ResourceWarning")


@pytest.fixture(scope="module")
def cluster():
    from spark_rapids_tpu.shuffle.cluster import LocalCluster
    cl = LocalCluster(2)
    yield cl
    cl.shutdown()


def _sales(n=20000, seed=3):
    rng = np.random.RandomState(seed)
    return pa.table({
        "k": pa.array(rng.randint(0, 23, n)),
        "g": pa.array(rng.choice(["x", "y", "z"], n)),
        "v": pa.array(np.round(rng.uniform(0, 100, n), 2)),
    })


def test_transport_put_fetch_roundtrip():
    from spark_rapids_tpu.shuffle.transport import BlockClient, BlockServer
    srv = BlockServer()
    try:
        c = BlockClient(srv.address)
        c.put(7, 0, b"alpha")
        c.put(7, 0, b"beta")
        c.put(7, 1, b"gamma")
        assert c.fetch(7, 0) == [b"alpha", b"beta"]
        assert c.fetch(7, 1) == [b"gamma"]
        assert c.fetch(7, 2) == []
        c.drop(7)
        assert c.fetch(7, 0) == []
        c.close()
    finally:
        srv.close()


def test_heartbeat_discovers_peers(cluster):
    # the driver registry saw both workers; each worker connected to the
    # other through on_new_peer (heartbeat.py's production caller)
    assert len(cluster.manager.live_peers()) == 2
    for c in cluster.clients.values():
        peers = c.task("heartbeat")
        assert len(peers) == 1          # the OTHER worker connected


def test_distributed_grouped_agg_differential(cluster):
    t = _sales()
    s = tpu_session()
    df = (s.create_dataframe(t).group_by("k", "g")
          .agg(F.sum(F.col("v")).with_name("sv"),
               F.count_star().with_name("n"),
               F.avg(F.col("v")).with_name("av"),
               F.min(F.col("v")).with_name("mn"),
               F.max(F.col("v")).with_name("mx")))
    got = cluster.execute(df).to_pandas() \
        .sort_values(["k", "g"]).reset_index(drop=True)
    want = df.collect_arrow().to_pandas() \
        .sort_values(["k", "g"]).reset_index(drop=True)
    assert list(got.columns) == list(want.columns)
    np.testing.assert_array_equal(got["k"], want["k"])
    np.testing.assert_array_equal(got["g"], want["g"])
    np.testing.assert_array_equal(got["n"], want["n"])
    for c in ("sv", "av", "mn", "mx"):
        np.testing.assert_allclose(got[c], want[c], rtol=1e-9)


def test_distributed_q3_two_processes(cluster):
    """TPC-DS q3 across 2 worker processes: fact scan sliced, dims
    broadcast, partial aggregates shuffled over TCP, driver finishes the
    order-by (VERDICT r1 #7 'done' criterion)."""
    import sys
    sys.path.insert(0, ".")
    from benchmarks import tpcds
    ss = tpcds.gen_store_sales(30000)
    s = tpu_session()
    q = tpcds.q3(s.create_dataframe(ss),
                 s.create_dataframe(tpcds.gen_date_dim()),
                 s.create_dataframe(tpcds.gen_item()), F)
    got = cluster.execute(q).to_pandas()
    want = q.collect_arrow().to_pandas()
    assert len(got) == len(want)
    np.testing.assert_array_equal(got["d_year"], want["d_year"])
    np.testing.assert_array_equal(got["i_brand"], want["i_brand"])
    np.testing.assert_allclose(got["sum_agg"], want["sum_agg"], rtol=1e-9)


def test_distributed_global_agg(cluster):
    t = _sales(5000)
    s = tpu_session()
    df = s.create_dataframe(t).agg(F.sum(F.col("v")).with_name("s"),
                                   F.count_star().with_name("n"))
    got = cluster.execute(df).to_pylist()
    want = df.collect()
    assert got[0]["n"] == want[0]["n"]
    np.testing.assert_allclose(got[0]["s"], want[0]["s"], rtol=1e-12)


def test_hash_partition_normalizes_float_keys():
    """-0.0/0.0 and differing NaN payloads must route to the SAME
    partition or distributed grouping emits duplicate groups
    (advisor r2)."""
    from spark_rapids_tpu.exprs import ColumnRef
    from spark_rapids_tpu.shuffle.cluster import _hash_partition
    nan_a = np.uint64(0x7FF8000000000001).view(np.float64)
    t = pa.table({"k": pa.array([0.0, -0.0, np.nan, float(nan_a), 1.5]),
                  "v": pa.array([1, 2, 3, 4, 5])})
    parts = _hash_partition(t, [ColumnRef("k")], 4)
    home = {}
    for p, sub in parts.items():
        for k in sub.column("v").to_pylist():
            home[k] = p
    assert home[1] == home[2], "-0.0 and 0.0 split across partitions"
    assert home[3] == home[4], "NaN payloads split across partitions"


def test_transport_rejects_unauthenticated_and_unknown_tasks():
    """A tokened server refuses unsigned/mis-signed traffic, and the
    task op only reaches REGISTERED names (advisor r2: no arbitrary
    callable execution)."""
    from spark_rapids_tpu.shuffle.transport import BlockClient, BlockServer
    srv = BlockServer(token=b"s3cret", tasks={"echo": lambda x: x})
    try:
        good = BlockClient(srv.address, token=b"s3cret")
        good.put(1, 0, b"data")
        assert good.fetch(1, 0) == [b"data"]
        assert good.task("echo", x=41) == 41
        with pytest.raises(RuntimeError, match="unknown task"):
            good.task("os_system", x="rm -rf /")
        bad = BlockClient(srv.address, token=b"wrong")
        with pytest.raises((ConnectionError, OSError)):
            bad.put(1, 0, b"evil")
        unsigned = BlockClient(srv.address)  # no token at all
        with pytest.raises((ConnectionError, OSError)):
            unsigned.fetch(1, 0)
        good.close()
    finally:
        srv.close()


@pytest.fixture(scope="module")
def cluster4():
    from spark_rapids_tpu.shuffle.cluster import LocalCluster
    cl = LocalCluster(4, shuffle_join_min_rows=1000)
    yield cl
    cl.shutdown()


def test_shuffled_join_agg_differential(cluster4):
    """join+agg across LocalCluster(4) with BOTH sides hash-partitioned
    by join key (VERDICT r2 #5 'done' criterion): results identical to
    single-process."""
    rng = np.random.RandomState(7)
    n = 40000
    left = pa.table({
        "k": pa.array(rng.randint(0, 5000, n)),
        "v": pa.array(np.round(rng.uniform(0, 10, n), 2)),
    })
    right = pa.table({
        "k2": pa.array(rng.randint(0, 5000, n)),
        "w": pa.array(rng.randint(0, 100, n)),
    })
    s = tpu_session()
    df = (s.create_dataframe(left)
          .join(s.create_dataframe(right),
                on=[(F.col("k"), F.col("k2"))], how="inner")
          .group_by("k")
          .agg(F.sum(F.col("v")).with_name("sv"),
               F.count_star().with_name("n"),
               F.max(F.col("w")).with_name("mw")))
    got = cluster4.execute(df).to_pandas() \
        .sort_values("k").reset_index(drop=True)
    want = df.collect_arrow().to_pandas() \
        .sort_values("k").reset_index(drop=True)
    assert len(got) == len(want)
    np.testing.assert_array_equal(got["k"], want["k"])
    np.testing.assert_array_equal(got["n"], want["n"])
    np.testing.assert_array_equal(got["mw"], want["mw"])
    np.testing.assert_allclose(got["sv"], want["sv"], rtol=1e-9)


def test_shuffled_left_join_null_keys(cluster4):
    """Left-join rows with NULL keys survive the shuffle (routed to a
    deterministic partition, never matched)."""
    left = pa.table({"k": pa.array([1, 2, None, 4] * 500),
                     "v": pa.array([1.0, 2.0, 3.0, 4.0] * 500)})
    right = pa.table({"k2": pa.array([1, 4] * 600),
                      "w": pa.array([10, 40] * 600)})
    s = tpu_session()
    df = (s.create_dataframe(left)
          .join(s.create_dataframe(right),
                on=[(F.col("k"), F.col("k2"))], how="left")
          .group_by("k")
          .agg(F.count_star().with_name("n"),
               F.sum(F.col("w")).with_name("sw")))
    got = cluster4.execute(df).to_pandas()
    want = df.collect_arrow().to_pandas()
    gk = got.sort_values("k", na_position="last").reset_index(drop=True)
    wk = want.sort_values("k", na_position="last").reset_index(drop=True)
    np.testing.assert_array_equal(gk["n"], wk["n"])


def test_remote_task_errors_wrapped_not_mistaken_for_dead_worker():
    """A worker-side OSError (disk full, file IO) must surface as
    RemoteTaskError, NOT as a raw OSError/ConnectionError — the
    scheduler's death classifier only trusts genuine socket failures,
    or a deterministic worker error would get healthy workers declared
    dead one by one. ShuffleFetchFailed stays verbatim (it IS the
    recovery signal), and non-round-trippable exceptions degrade to
    their repr instead of crashing the driver's unpickler."""
    from spark_rapids_tpu.shuffle.transport import (BlockClient,
                                                    BlockServer,
                                                    RemoteTaskError,
                                                    ShuffleFetchFailed)

    class NoRoundTrip(Exception):
        def __init__(self, a, b):   # pickles, but cannot rebuild from
            super().__init__(a)     # its (single-arg) args tuple

    def boom_os():
        raise OSError(28, "No space left on device")

    def boom_fetch():
        raise ShuffleFetchFailed("blocks gone", peer="worker-9")

    def boom_weird():
        raise NoRoundTrip(1, 2)

    srv = BlockServer(token=b"t", tasks={"os": boom_os,
                                         "fetch": boom_fetch,
                                         "weird": boom_weird,
                                         "echo": lambda x: x})
    c = BlockClient(srv.address, token=b"t", timeout=10)
    try:
        with pytest.raises(RemoteTaskError, match="No space left"):
            c.task("os")
        with pytest.raises(ShuffleFetchFailed) as ei:
            c.task("fetch")
        assert ei.value.peer == "worker-9"
        with pytest.raises(RuntimeError, match="NoRoundTrip"):
            c.task("weird")
        assert c.task("echo", x=7) == 7    # connection survives errors
    finally:
        c.close()
        srv.close()


def test_heartbeat_staleness_evicts_and_rereg_recovers():
    """An executor that stops heartbeating leaves live_peers() after
    stale_after_s; a fresh heartbeat re-registers it cleanly (ref
    RapidsShuffleHeartbeatManager eviction, Plugin.scala:428-439)."""
    import time
    from spark_rapids_tpu.shuffle.heartbeat import ShuffleHeartbeatManager
    mgr = ShuffleHeartbeatManager(stale_after_s=0.15)
    mgr.register("ex-0", {"host": "h0", "port": 1})
    mgr.register("ex-1", {"host": "h1", "port": 2})
    assert mgr.live_peers() == ["ex-0", "ex-1"]
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        time.sleep(0.05)
        mgr.register("ex-0", {"host": "h0", "port": 1})  # ex-0 keeps beating
        if mgr.live_peers() == ["ex-0"]:
            break
    assert mgr.live_peers() == ["ex-0"], "stale ex-1 never evicted"
    # eviction also reflected in peer_details (dispatch reads this)
    assert [p["id"] for p in mgr.peer_details()] == ["ex-0"]
    # a re-registering executor comes back with its new address
    peers = mgr.register("ex-1", {"host": "h1b", "port": 3})
    assert {p["id"] for p in peers} == {"ex-0", "ex-1"}
    assert mgr.live_peers() == ["ex-0", "ex-1"]
    details = {p["id"]: p["addr"] for p in mgr.peer_details()}
    assert details["ex-1"] == {"host": "h1b", "port": 3}


def test_heartbeat_eviction_of_all_peers_when_silent():
    from spark_rapids_tpu.shuffle.heartbeat import ShuffleHeartbeatManager
    mgr = ShuffleHeartbeatManager(stale_after_s=0.05)
    mgr.register("ex-0", {"host": "h", "port": 1})
    import time
    time.sleep(0.2)
    assert mgr.live_peers() == []


def test_shutdown_escalates_to_sigkill_for_stopped_worker():
    """A SIGSTOPped (wedged) worker must not hang shutdown: join times
    out, SIGTERM stays pending on a stopped process, and the final
    SIGKILL is delivered regardless — teardown always completes."""
    import os
    import signal
    import time
    from spark_rapids_tpu.shuffle.cluster import LocalCluster
    cl = LocalCluster(1)
    proc = cl.procs[0]
    os.kill(proc.pid, signal.SIGSTOP)
    t0 = time.monotonic()
    cl.shutdown(join_timeout_s=1.0)
    elapsed = time.monotonic() - t0
    assert not proc.is_alive(), "stopped worker survived shutdown"
    assert elapsed < 30, f"shutdown escalation took {elapsed:.1f}s"


def test_fetch_failure_surfaces_cleanly():
    """A dead peer mid-shuffle raises ShuffleFetchFailed, not a hang
    (ref RapidsShuffleIterator transport-error handling)."""
    from spark_rapids_tpu.shuffle.transport import (BlockClient,
                                                    BlockServer,
                                                    ShuffleFetchFailed)
    srv = BlockServer(token=b"t")
    c = BlockClient(srv.address, token=b"t")
    c.put(9, 0, b"block")
    srv.close()           # peer dies
    with pytest.raises(ShuffleFetchFailed):
        for _ in range(3):     # first fetch may see a half-open socket
            c.fetch(9, 0)


# ---------------------------------------------------------------------------
# cross-process sorts and windows (r4: VERDICT #6 — the shuffle grammar
# covers more than agg/join; ref range-partitioned sort + hash-partitioned
# windows through RapidsShuffleInternalManagerBase.scala:238-614)
# ---------------------------------------------------------------------------

def _win_table(n=12000, seed=9):
    rng = np.random.RandomState(seed)
    return pa.table({
        "p": pa.array(rng.randint(0, 64, n)),
        "o": pa.array(rng.randint(0, 1 << 20, n)),
        "v": pa.array(np.round(rng.uniform(-50, 50, n), 3)),
    })


def test_distributed_sort_differential(cluster):
    s = tpu_session()
    t = _sales(30000)
    df = (s.create_dataframe(t)
          .filter(F.col("v") > 5.0)
          .order_by(F.col("v").asc(), F.col("k").asc()))
    got = cluster.execute(df).to_pandas().reset_index(drop=True)
    want = df.to_pandas().reset_index(drop=True)
    assert len(got) == len(want)
    np.testing.assert_allclose(got["v"], want["v"])
    np.testing.assert_array_equal(got["k"], want["k"])


def test_distributed_sort_desc_with_limit(cluster):
    s = tpu_session()
    t = _sales(30000)
    df = s.create_dataframe(t).order_by(F.col("v").desc()).limit(50)
    got = cluster.execute(df).to_pandas().reset_index(drop=True)
    want = df.to_pandas().reset_index(drop=True)
    assert len(got) == 50
    np.testing.assert_allclose(got["v"], want["v"])


def test_distributed_sort_string_key_with_nulls(cluster):
    rng = np.random.RandomState(5)
    vals = rng.choice(["aa", "bb", "cc", "dd", None], 8000)
    t = pa.table({"s": pa.array(vals),
                  "v": pa.array(rng.uniform(0, 1, 8000))})
    s = tpu_session()
    df = s.create_dataframe(t).order_by(F.col("s").asc())
    got = cluster.execute(df).to_pandas().reset_index(drop=True)
    want = df.to_pandas().reset_index(drop=True)
    np.testing.assert_array_equal(got["s"].isna(), want["s"].isna())
    np.testing.assert_array_equal(got["s"].dropna(), want["s"].dropna())


def test_distributed_window_differential(cluster):
    from spark_rapids_tpu.exprs import ColumnRef
    from spark_rapids_tpu.exprs.aggregates import Sum

    def q(s):
        return (s.create_dataframe(_win_table())
                .with_window_column("ws", Sum(ColumnRef("v")),
                                    partition_by=["p"],
                                    order_by=[F.col("o").asc()],
                                    frame=("rows", -2, 0)))
    s = tpu_session()
    df = q(s)
    got = (cluster.execute(df).to_pandas()
           .sort_values(["p", "o"]).reset_index(drop=True))
    want = (df.to_pandas()
            .sort_values(["p", "o"]).reset_index(drop=True))
    assert len(got) == len(want)
    np.testing.assert_allclose(got["ws"], want["ws"], rtol=1e-9)


def test_distributed_window_requires_partition_keys(cluster):
    from spark_rapids_tpu.exprs import ColumnRef
    from spark_rapids_tpu.exprs.aggregates import Sum
    s = tpu_session()
    df = (s.create_dataframe(_win_table(500))
          .with_window_column("ws", Sum(ColumnRef("v")),
                              partition_by=[],
                              order_by=[F.col("o").asc()],
                              frame=("rows", -2, 0)))
    with pytest.raises(ValueError, match="partition_by"):
        cluster.execute(df)


# ---------------------------------------------------------------------------
# multi-host seam (r4: VERDICT #9): non-loopback bind + externally-launched
# standalone workers over the authenticated typed-task protocol
# ---------------------------------------------------------------------------

def _non_loopback_ip():
    import socket
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
        s.close()
        return None if ip.startswith("127.") else ip
    except OSError:
        return None


def test_multihost_standalone_workers_differential(tmp_path):
    """Driver bound to a real interface; two workers join via the
    `python -m spark_rapids_tpu.shuffle.worker` entry point (separate
    processes, non-loopback TCP — the two-'host' simulation). The
    distributed aggregate must match the local engine."""
    import os
    import subprocess
    import sys
    ip = _non_loopback_ip()
    if ip is None:
        pytest.skip("no non-loopback interface")
    from spark_rapids_tpu.shuffle.cluster import LocalCluster
    cl = LocalCluster(n_workers=0, bind_host=ip)
    tok = tmp_path / "token"
    tok.write_bytes(cl.token)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, "-m", "spark_rapids_tpu.shuffle.worker",
         "--driver", f"{ip}:{cl.control.address[1]}",
         "--token-file", str(tok), "--id", str(i), "--bind", ip],
        env=env) for i in range(2)]
    try:
        cl.wait_for_workers(2, timeout_s=60)
        assert all(a[0] == ip for a in cl.workers.values()), cl.workers
        s = tpu_session()
        t = _sales(20000)
        df = (s.create_dataframe(t).group_by("g")
              .agg(F.sum(F.col("v")).with_name("sv"),
                   F.count_star().with_name("n")))
        got = cl.execute(df).to_pandas().sort_values("g") \
            .reset_index(drop=True)
        want = df.to_pandas().sort_values("g").reset_index(drop=True)
        assert len(got) == len(want)
        np.testing.assert_allclose(got["sv"], want["sv"], rtol=1e-9)
        np.testing.assert_array_equal(got["n"], want["n"])
    finally:
        cl.shutdown()
        for p in procs:
            p.terminate()
            p.wait(timeout=10)


def _netns_available():
    import os
    import shutil
    import subprocess
    if shutil.which("ip") is None or os.geteuid() != 0:
        return False
    r = subprocess.run(["ip", "netns", "list"], capture_output=True)
    return r.returncode == 0


def _run_standalone_workers_differential(tmp_path, bind_ip, worker_ip,
                                         exec_prefix):
    """Shared driver/worker scaffolding for the standalone-worker tests:
    spawn two `python -m spark_rapids_tpu.shuffle.worker` processes
    (optionally wrapped by ``exec_prefix``, e.g. `ip netns exec ...`),
    run a grouped aggregate through the cluster, compare with the local
    engine, and tear everything down even on partial setup failure."""
    import os
    import subprocess
    import sys
    from spark_rapids_tpu.shuffle.cluster import LocalCluster
    cl = None
    procs = []
    try:
        cl = LocalCluster(n_workers=0, bind_host=bind_ip)
        tok = tmp_path / "token"
        tok.write_bytes(cl.token)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))) + os.pathsep + \
            env.get("PYTHONPATH", "")
        procs = [subprocess.Popen(
            list(exec_prefix) + [sys.executable, "-m",
             "spark_rapids_tpu.shuffle.worker",
             "--driver", f"{bind_ip}:{cl.control.address[1]}",
             "--token-file", str(tok), "--id", str(i),
             "--bind", worker_ip], env=env) for i in range(2)]
        cl.wait_for_workers(2, timeout_s=90)
        assert all(a[0] == worker_ip for a in cl.workers.values()), \
            cl.workers
        s = tpu_session()
        t = _sales(20000)
        df = (s.create_dataframe(t).group_by("k", "g")
              .agg(F.sum(F.col("v")).with_name("sv"),
                   F.count_star().with_name("n")))
        got = cl.execute(df).to_pandas() \
            .sort_values(["k", "g"]).reset_index(drop=True)
        want = df.to_pandas().sort_values(["k", "g"]) \
            .reset_index(drop=True)
        assert len(got) == len(want)
        np.testing.assert_allclose(got["sv"], want["sv"], rtol=1e-9)
        np.testing.assert_array_equal(got["n"], want["n"])
    finally:
        if cl is not None:
            try:
                cl.shutdown()
            except Exception:
                pass
        for p in procs:
            p.terminate()
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()        # last resort: never leak a root worker
                try:
                    p.wait(timeout=10)
                except Exception:
                    pass


@pytest.mark.skipif(not _netns_available(),
                    reason="requires root + iproute2 network namespaces")
def test_cross_network_namespace_workers_differential(tmp_path):
    """r5 (VERDICT r4 missing #3, DCN-analog): the driver and workers
    run in SEPARATE network namespaces over a veth pair — two distinct
    network stacks exchanging shuffle blocks across the veth subnet,
    the closest to a true multi-host run a single box allows (ref
    shuffle-plugin RapidsShuffleTransport multi-executor exchange)."""
    import os
    import subprocess
    pid = os.getpid() % 10000
    ns = f"srtpu-{pid}"
    veth_h, veth_w = f"vsr{pid}h"[:15], f"vsr{pid}w"[:15]
    host_ip, w_ip = "10.77.1.1", "10.77.1.2"

    def sh(*cmd):
        r = subprocess.run(cmd, capture_output=True, text=True)
        assert r.returncode == 0, f"{cmd}: {r.stderr}"

    try:
        sh("ip", "netns", "add", ns)
        sh("ip", "link", "add", veth_h, "type", "veth",
           "peer", "name", veth_w)
        sh("ip", "link", "set", veth_w, "netns", ns)
        sh("ip", "addr", "add", f"{host_ip}/24", "dev", veth_h)
        sh("ip", "link", "set", veth_h, "up")
        sh("ip", "netns", "exec", ns, "ip", "addr", "add",
           f"{w_ip}/24", "dev", veth_w)
        sh("ip", "netns", "exec", ns, "ip", "link", "set", veth_w, "up")
        sh("ip", "netns", "exec", ns, "ip", "link", "set", "lo", "up")
        _run_standalone_workers_differential(
            tmp_path, host_ip, w_ip, ["ip", "netns", "exec", ns])
    finally:
        subprocess.run(["ip", "netns", "del", ns], capture_output=True)
        subprocess.run(["ip", "link", "del", veth_h],
                       capture_output=True)
