"""Native (C++) OOM state machine tests — the RmmSpark-analog layer
(spark_rapids_tpu/native/oom_state.cpp via ctypes)."""
import threading
import time

import pytest

from spark_rapids_tpu.mem.native import NativeOomState, load


pytestmark = pytest.mark.skipif(load() is None,
                                reason="no g++ toolchain available")


@pytest.fixture
def st():
    yield NativeOomState(1000)
    # the native machine is process-global: restore the singleton manager's
    # budget so later query tests aren't squeezed into 1000 bytes
    from spark_rapids_tpu.mem import MemoryManager
    for mm in MemoryManager._instances.values():
        if mm._native is not None:
            mm._native.lib.oom_init(mm.budget)


class TestNativeAccounting:
    def test_reserve_release(self, st):
        assert st.reserve(400) == 0
        assert st.used == 400
        assert st.reserve(600) == 0
        assert st.used == 1000
        assert st.reserve(1) == 1  # full -> retry
        st.release(500)
        assert st.reserve(1) == 0
        assert st.max_used == 1000

    def test_oversized_is_split(self, st):
        assert st.reserve(2000) == 2

    def test_injection_with_skip(self, st):
        st.force_retry_oom(2, skip=1)
        assert st.reserve(1) == 0   # skipped
        assert st.reserve(1) == 1   # injected
        assert st.reserve(1) == 1   # injected
        assert st.reserve(1) == 0
        assert st.retry_count() == 2

    def test_split_injection(self, st):
        st.force_split_and_retry_oom(1)
        assert st.reserve(1) == 2
        assert st.reserve(1) == 0

    def test_clear_injections(self, st):
        st.force_retry_oom(5)
        st.clear_injections()
        assert st.reserve(1) == 0


class TestNativeBlocking:
    def test_blocked_thread_wakes_on_release(self, st):
        assert st.reserve(900) == 0
        results = {}

        def blocked():
            results["rc"] = st.reserve(500, block_ms=2000)

        t = threading.Thread(target=blocked)
        t.start()
        time.sleep(0.1)
        assert st.blocked_threads == 1
        st.release(900)  # wakes the waiter
        t.join(timeout=3)
        assert results["rc"] == 0
        assert st.used == 500

    def test_block_timeout(self, st):
        assert st.reserve(1000) == 0
        t0 = time.perf_counter()
        assert st.reserve(500, block_ms=100) == 3
        assert 0.05 < time.perf_counter() - t0 < 1.0


def test_singleton_manager_uses_native():
    from spark_rapids_tpu.mem import MemoryManager
    mm = MemoryManager.get()
    assert mm._native is not None
