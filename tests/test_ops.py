"""Live ops plane tests (ISSUE 15): HTTP endpoint round-trips, flight
recorder bundles, regression sentinel, tools/regress goldens, and the
queryEnd reason/degraded satellite."""
import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pyarrow as pa
import pytest

from harness import tpu_session
from spark_rapids_tpu.api import functions as F

_RNG = np.random.RandomState(15)
_N = 2048
_T = pa.table({
    "k": pa.array(_RNG.randint(0, 13, _N)),
    "v": pa.array(_RNG.randint(0, 1000, _N).astype(np.int64)),
    "u": pa.array(np.arange(_N)),
})

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
BUNDLE_SECTIONS = ["config.json", "metrics.json", "placement.json",
                   "state.json", "trace.json"]


def _get(port, path, timeout=10):
    r = urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                               timeout=timeout)
    return r.status, r.read().decode("utf-8")


def _get_any(port, path, timeout=10):
    """GET tolerating non-2xx replies (healthz serves 503 when any
    section is degraded — under full-suite ordering, leftovers from
    earlier tests (dead holders, drained budgets) can legitimately
    degrade process-wide sections)."""
    try:
        return _get(port, path, timeout)
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")


def _start_server():
    from spark_rapids_tpu.ops import server as srv_mod
    return srv_mod.install_ops(srv_mod.OpsServer(0).start())


def _agg_df(s):
    return (s.create_dataframe(_T, num_partitions=2).group_by("k")
            .agg(F.sum(F.col("v")).with_name("sv"),
                 F.count_star().with_name("n")))


# ---------------------------------------------------------------------------
# disabled path
# ---------------------------------------------------------------------------

def test_disabled_path_no_threads_no_recorder():
    """No ops conf: no server thread, no recorder, no sentinel — every
    instrumented site sees a None module global."""
    from spark_rapids_tpu.ops import flight as fl_mod
    from spark_rapids_tpu.ops import sentinel as sen_mod
    from spark_rapids_tpu.ops import server as srv_mod
    before = {t.name for t in threading.enumerate()}
    s = tpu_session()
    _agg_df(s).collect_arrow()
    assert srv_mod.SERVER is None
    assert fl_mod.RECORDER is None
    assert sen_mod.SENTINEL is None
    after = {t.name for t in threading.enumerate()}
    assert not [n for n in after - before if n.startswith("srtpu-ops")]


def test_conf_gated_server_install(tmp_path):
    """spark.rapids.tpu.ops.port > 0 starts the daemon thread once and
    serves; port 0 (default) never does."""
    import socket
    from spark_rapids_tpu.ops import server as srv_mod
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    s = tpu_session({"spark.rapids.tpu.ops.port": port})
    _agg_df(s).collect_arrow()
    assert srv_mod.SERVER is not None and srv_mod.SERVER.port == port
    status, body = _get_any(port, "/healthz")
    doc = json.loads(body)
    # 200/ok in a fresh process; earlier tests in a shared suite run
    # may leave legitimately-degraded process-wide state (503)
    assert (status, doc["status"]) in ((200, "ok"), (503, "degraded"))
    assert "semaphore" in doc and "memory" in doc
    # idempotent: a second session re-uses the same server
    s2 = tpu_session({"spark.rapids.tpu.ops.port": port})
    s2.exec_context()
    assert srv_mod.SERVER.port == port


# ---------------------------------------------------------------------------
# /metrics over real HTTP (satellite: exposition round-trip)
# ---------------------------------------------------------------------------

def test_metrics_exposition_http_roundtrip():
    """Label escaping and histogram bucket invariants survive the wire:
    what a real Prometheus scrape of the ops server sees parses back to
    the registry's own exposition."""
    from spark_rapids_tpu.metrics import (install_metrics,
                                          prometheus_text,
                                          registry_snapshot)
    from spark_rapids_tpu.metrics.registry import MetricRegistry
    reg = install_metrics(MetricRegistry())
    reg.counter("srtpu_queries_total", status='we"ird\\la\nbel').inc(3)
    h = reg.histogram("srtpu_query_seconds")
    for v in (0.003, 0.04, 0.8, 2.0, 120.0):
        h.observe(v)
    srv = _start_server()
    status, body = _get(srv.port, "/metrics")
    assert status == 200
    local = prometheus_text(registry_snapshot(reg))
    assert body == local
    # escaping: backslash, quote, newline all encoded per the text spec
    assert 'status="we\\"ird\\\\la\\nbel"' in body
    lines = body.splitlines()
    # exposition-level invariants over the wire
    buckets = []
    count = hsum = None
    for ln in lines:
        if ln.startswith("srtpu_query_seconds_bucket"):
            le = ln.split('le="', 1)[1].split('"', 1)[0]
            buckets.append((le, float(ln.rsplit(" ", 1)[1])))
        elif ln.startswith("srtpu_query_seconds_count"):
            count = float(ln.rsplit(" ", 1)[1])
        elif ln.startswith("srtpu_query_seconds_sum"):
            hsum = float(ln.rsplit(" ", 1)[1])
    assert buckets and buckets[-1][0] == "+Inf"
    counts = [c for _, c in buckets]
    assert counts == sorted(counts), "buckets must be cumulative"
    assert count == 5 and buckets[-1][1] == count
    assert hsum == pytest.approx(122.843)
    # HELP/TYPE headers present for every family
    assert "# TYPE srtpu_query_seconds histogram" in body
    assert "# TYPE srtpu_queries_total counter" in body
    # the scrape itself is counted once installed
    status2, body2 = _get(srv.port, "/metrics")
    assert 'srtpu_ops_requests_total{endpoint="/metrics"} ' in body2


def test_metrics_endpoint_without_registry():
    srv = _start_server()
    status, body = _get(srv.port, "/metrics")
    assert status == 200 and "no metric registry" in body


def test_ops_endpoint_survives_broken_registry(monkeypatch):
    """never-raise regression (tpulint v3 crop): the request-counter
    fan-out sits INSIDE do_GET's guarded body — a raising registry
    degrades to a 500 JSON error instead of escaping into
    socketserver's handle_error (stderr traceback + dropped
    connection)."""
    from spark_rapids_tpu.metrics import registry as metrics_registry

    class _Boom:
        def counter(self, *a, **k):
            raise RuntimeError("registry exploded")

    srv = _start_server()
    monkeypatch.setattr(metrics_registry, "REGISTRY", _Boom())
    status, body = _get_any(srv.port, "/metrics")
    assert status == 500
    assert "registry exploded" in json.loads(body)["error"]


# ---------------------------------------------------------------------------
# /healthz + /queries
# ---------------------------------------------------------------------------

def test_healthz_sections_and_degraded_semaphore():
    from spark_rapids_tpu.mem import DeviceSemaphore
    srv = _start_server()
    status0, body0 = _get_any(srv.port, "/healthz")
    doc0 = json.loads(body0)
    for section in ("semaphore", "memory", "admission", "execCache",
                    "workers", "eventLog", "flight", "sentinel"):
        assert doc0[section]["verdict"] in ("ok", "degraded"), section
    # the report is internally consistent: 200 iff every section ok
    all_ok = all(doc0[s]["verdict"] == "ok" for s in
                 ("semaphore", "memory", "admission", "execCache",
                  "workers", "eventLog", "flight", "sentinel"))
    assert (status0 == 200) == all_ok == (doc0["status"] == "ok")
    dead0 = doc0["semaphore"]["deadHolders"]
    # a holder thread that died without releasing degrades /healthz
    sem = DeviceSemaphore(2, timeout_s=30.0, wedge_timeout_ms=0)
    t = threading.Thread(target=sem.acquire, name="dead-holder")
    t.start()
    t.join()
    try:
        code, body = _get_any(srv.port, "/healthz")
        assert code == 503
        doc = json.loads(body)
        assert doc["status"] == "degraded"
        assert doc["semaphore"]["verdict"] == "degraded"
        assert doc["semaphore"]["deadHolders"] >= dead0 + 1
    finally:
        sem.check_wedged()     # reclaim so later tests see a clean sem


def test_queries_endpoint_tracks_history():
    srv = _start_server()
    s = tpu_session()
    for _ in range(3):
        _agg_df(s).collect_arrow()
    status, body = _get(srv.port, "/queries")
    doc = json.loads(body)
    assert status == 200
    assert doc["inflight"] == []
    assert len(doc["recent"]) == 3
    rec = doc["recent"][-1]
    assert rec["status"] == "ok" and rec["wallMs"] > 0
    assert rec["planDigest"] and rec["ladderRung"] == 0
    assert rec["placement"] in ("device", "host")
    assert rec["root"] == "Aggregate"
    # a failing query lands with status failed + reason
    with pytest.raises(Exception):
        s.create_dataframe(_T).select(F.col("nope")).collect_arrow()
    doc = json.loads(_get(srv.port, "/queries")[1])
    # planning fails before the tracker begins: only executed queries
    # appear — run one that fails DURING execution instead
    def boom(pdf):
        raise RuntimeError("kaboom")
    with pytest.raises(Exception):
        s.create_dataframe(_T).map_in_pandas(boom, _T.schema) \
            .collect_arrow()
    doc = json.loads(_get(srv.port, "/queries")[1])
    failed = [r for r in doc["recent"] if r["status"] == "failed"]
    assert failed and "kaboom" in failed[-1]["reason"]


def test_healthz_event_log_lag(tmp_path):
    from spark_rapids_tpu.metrics.events import EventLogWriter
    srv = _start_server()
    w = EventLogWriter(str(tmp_path / "elog"))
    w.write({"event": "queryStart", "queryId": 1})
    doc = json.loads(_get_any(srv.port, "/healthz")[1])
    writers = [x for x in doc["eventLog"]["writers"]
               if x["dir"] == str(tmp_path / "elog")]
    assert writers and writers[0]["lagS"] >= 0
    assert writers[0]["lastErrorTs"] is None
    # a writer whose newest attempt FAILS degrades the section
    bad = EventLogWriter(str(tmp_path / "not-a-dir" / ("x" * 300)))
    assert bad.write({"event": "queryStart"}) is False
    doc = json.loads(_get_any(srv.port, "/healthz")[1])
    assert doc["eventLog"]["verdict"] == "degraded"


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def _mk_recorder(tmp_path, rate_limit_ms=60000, conf=None):
    from spark_rapids_tpu.ops import flight as fl_mod
    rec = fl_mod.FlightRecorder(str(tmp_path / "flight"),
                                rate_limit_ms=rate_limit_ms, conf=conf)
    return fl_mod.install_flight(rec)


def test_flight_bundle_sections_and_atomicity(tmp_path):
    rec = _mk_recorder(tmp_path)
    path = rec.trigger("semaphore_wedge", detail="unit test")
    assert path and os.path.isdir(path)
    assert sorted(os.listdir(path)) == BUNDLE_SECTIONS
    # no temp droppings next to the committed bundle
    assert all(not n.startswith(".tmp-")
               for n in os.listdir(os.path.dirname(path)))
    state = json.load(open(os.path.join(path, "state.json")))
    assert "memory" in state and "execCache" in state
    assert "pressure_granted" in state["memory"]
    placement = json.load(open(os.path.join(path, "placement.json")))
    assert placement["trigger"] == "semaphore_wedge"
    assert placement["detail"] == "unit test"
    trace = json.load(open(os.path.join(path, "trace.json")))
    assert any(b["kind"] == "flight.trigger"
               for b in trace["breadcrumbs"])


def test_flight_rate_limit_and_unknown_kind(tmp_path):
    rec = _mk_recorder(tmp_path, rate_limit_ms=60000)
    p1 = rec.trigger("oom_ladder", detail="first")
    p2 = rec.trigger("oom_ladder", detail="suppressed")
    p3 = rec.trigger("query_timeout", detail="different kind")
    assert p1 and p3 and p2 is None
    st = rec.stats()
    assert st["dumps"] == {"oom_ladder": 1, "query_timeout": 1}
    assert st["suppressed"] == {"oom_ladder": 1}
    with pytest.raises(ValueError):
        rec.trigger("not_a_registered_kind")


def test_flight_config_redaction(tmp_path):
    from spark_rapids_tpu.config import TpuConf
    conf = TpuConf({"spark.rapids.tpu.eventLog.dir": "/data/elog",
                    "spark.hadoop.fs.s3a.secret.key": "SUPERSECRET",
                    "my.service.authToken": "abc123"})
    rec = _mk_recorder(tmp_path, conf=conf)
    path = rec.trigger("worker_evicted", detail="redaction test")
    cfg = json.load(open(os.path.join(path, "config.json")))
    ov = cfg["overridesFromDefaults"]
    assert ov["spark.rapids.tpu.eventLog.dir"] == "/data/elog"
    assert ov["spark.hadoop.fs.s3a.secret.key"] == "<redacted>"
    assert ov["my.service.authToken"] == "<redacted>"
    assert "SUPERSECRET" not in json.dumps(cfg)


def test_flight_metric_declared_and_counted(tmp_path):
    from spark_rapids_tpu.metrics import install_metrics
    from spark_rapids_tpu.metrics.registry import MetricRegistry
    reg = install_metrics(MetricRegistry())
    rec = _mk_recorder(tmp_path)
    rec.trigger("placement_revert", detail="x")
    snap = reg.snapshot()
    series = snap["srtpu_flight_dumps_total"]["series"]
    assert [s for s in series
            if s["labels"] == {"trigger": "placement_revert"}
            and s["value"] == 1]


def test_warm_digest_recompile_trigger(tmp_path):
    """A digest in the compiled-plan set that pays backend-compile
    seconds anyway fires the warm_recompile trigger; a cold digest
    paying the same compile does not."""
    from spark_rapids_tpu.ops import flight as fl_mod
    from spark_rapids_tpu.plan import exec_cache

    def fake_compile(pdf):
        # runs MID-QUERY: simulates jax reporting real XLA compile work
        exec_cache._on_duration(
            "/jax/core/compile/backend_compile_duration", duration=0.25)
        return pdf

    s = tpu_session({"spark.rapids.tpu.flight.enabled": True,
                     "spark.rapids.tpu.flight.dir":
                         str(tmp_path / "flight"),
                     "spark.rapids.tpu.eventLog.enabled": True,
                     "spark.rapids.tpu.eventLog.dir":
                         str(tmp_path / "elog")})
    df = s.create_dataframe(_T).map_in_pandas(fake_compile, _T.schema)
    df.collect_arrow()                  # cold: digest unknown
    rec = fl_mod.RECORDER
    assert rec.stats()["dumps"].get("warm_recompile") is None
    from spark_rapids_tpu.tools.history import load_events
    events, _ = load_events(str(tmp_path / "elog"))
    digest = [e for e in events
              if e.get("event") == "queryStart"][-1]["planDigest"]
    exec_cache.record_plan_compiled(digest)   # now vouched warm
    df.collect_arrow()
    assert rec.stats()["dumps"].get("warm_recompile") == 1
    bundle = rec.stats()["bundles"][-1]
    placement = json.load(open(os.path.join(bundle, "placement.json")))
    assert digest in placement["detail"]


# ---------------------------------------------------------------------------
# sentinel
# ---------------------------------------------------------------------------

def test_sentinel_flags_and_persistence(tmp_path):
    from spark_rapids_tpu.metrics import install_metrics
    from spark_rapids_tpu.metrics.registry import MetricRegistry
    from spark_rapids_tpu.ops.sentinel import RegressionSentinel
    reg = install_metrics(MetricRegistry())
    rec = _mk_recorder(tmp_path)
    path = str(tmp_path / "baselines.json")
    sen = RegressionSentinel(path, wall_factor=3.0, min_samples=3)
    for ms in (100.0, 101.0, 99.0):
        assert sen.fold({"digest": "dA", "wallMs": ms,
                         "verdict": "device", "rung": 0, "ok": True}) == []
    regs = sen.fold({"digest": "dA", "wallMs": 500.0,
                     "verdict": "device", "rung": 0, "ok": True})
    # a 5x spike over a tight baseline trips both the median and the
    # tail check — the run is slower than 3x median AND 2x p99
    assert [r["kind"] for r in regs] == ["warm_slowdown",
                                         "tail_regression"]
    regs = sen.fold({"digest": "dA", "wallMs": 100.0,
                     "verdict": "host", "rung": 3, "ok": True})
    assert sorted(r["kind"] for r in regs) == ["rung_escalation",
                                               "verdict_flip"]
    # flight fan-out: the verdict flip uses the placement_revert trigger
    dumps = rec.stats()["dumps"]
    assert dumps.get("placement_revert") == 1
    assert dumps.get("sentinel_regression") == 1
    snap = reg.snapshot()
    kinds = {tuple(s["labels"].items())[0][1]: s["value"] for s in
             snap["srtpu_query_regressions_total"]["series"]}
    assert kinds == {"warm_slowdown": 1, "tail_regression": 1,
                     "verdict_flip": 1, "rung_escalation": 1}
    # persistence roundtrip: a fresh sentinel inherits the baselines
    sen2 = RegressionSentinel(path, wall_factor=3.0, min_samples=3)
    b = sen2.baselines()["dA"]
    assert b["verdict"] == "host" and b["maxRung"] == 3
    assert b["n"] == 5


def test_sentinel_cold_run_never_flags(tmp_path):
    from spark_rapids_tpu.ops.sentinel import fold_record
    baselines = {}
    for ms in (50.0, 51.0, 49.0):
        fold_record(baselines, {"digest": "d", "wallMs": ms,
                                "verdict": "device", "ok": True})
    # a compiling (cold) run is exempt from the slowdown check AND its
    # wall never pollutes the warm window
    regs = fold_record(baselines, {"digest": "d", "wallMs": 900.0,
                                   "verdict": "device", "ok": True,
                                   "compileS": 2.0})
    assert regs == []
    assert 900.0 not in baselines["d"]["walls"]
    # failed runs are exempt too
    regs = fold_record(baselines, {"digest": "d", "wallMs": 900.0,
                                   "verdict": "device", "ok": False})
    assert regs == []


def test_sentinel_save_tolerates_unserializable_baseline(tmp_path):
    """never-raise regression (tpulint v3 crop): a baseline record that
    picked up a non-JSON value (a numpy scalar riding in through a
    folded query record makes json.dump raise TypeError, not OSError)
    must degrade to an unsaved baseline, not raise out of the
    query-completion path that called fold()."""
    from spark_rapids_tpu.ops.sentinel import RegressionSentinel
    path = str(tmp_path / "b.json")
    sen = RegressionSentinel(path)
    with sen._lock:
        sen._baselines["d"] = {"walls": [], "poison": object()}
    assert sen.save() is False
    assert not os.path.exists(path)
    # the failed attempt's tmp file is cleaned up too
    assert [n for n in os.listdir(str(tmp_path))
            if n.startswith("b.json.tmp")] == []


def test_sentinel_fold_fanout_never_raises(tmp_path, monkeypatch):
    """never-raise regression (tpulint v3 crop): the flag fan-out
    (metrics counter + flight trigger + json.dumps of the flag record)
    is fallible; a raising recorder must not escape fold() — the regs
    still come back and the query completes."""
    from spark_rapids_tpu.ops import flight as fl_mod
    from spark_rapids_tpu.ops.sentinel import RegressionSentinel

    class _BoomRecorder:
        def trigger(self, kind, detail=None):
            raise RuntimeError("recorder exploded")

    monkeypatch.setattr(fl_mod, "RECORDER", _BoomRecorder())
    sen = RegressionSentinel(str(tmp_path / "b.json"), wall_factor=3.0,
                             min_samples=3)
    for ms in (100.0, 101.0, 99.0):
        assert sen.fold({"digest": "d", "wallMs": ms,
                         "verdict": "device", "ok": True}) == []
    regs = sen.fold({"digest": "d", "wallMs": 900.0,
                     "verdict": "device", "ok": True})
    assert [r["kind"] for r in regs] == ["warm_slowdown",
                                         "tail_regression"]


def test_sentinel_live_fold_from_queries(tmp_path):
    """The wired path: queries folded per queryEnd, baselines persisted
    beside the stats store path override."""
    from spark_rapids_tpu.ops import sentinel as sen_mod
    path = str(tmp_path / "b.json")
    s = tpu_session({"spark.rapids.tpu.sentinel.enabled": True,
                     "spark.rapids.tpu.sentinel.path": path})
    for _ in range(3):
        _agg_df(s).collect_arrow()
    sen = sen_mod.SENTINEL
    assert sen is not None and sen.path == path
    bl = sen.baselines()
    assert len(bl) == 1
    (b,) = bl.values()
    assert b["n"] == 3
    # persistence is debounced on clean folds; an explicit save lands
    assert sen.save() and os.path.exists(path)


# ---------------------------------------------------------------------------
# tools/regress
# ---------------------------------------------------------------------------

def test_regress_replay_golden(capsys):
    from spark_rapids_tpu.tools.history import load_events
    from spark_rapids_tpu.tools.regress import (format_replay,
                                                replay_events)
    fixture = os.path.join(FIXTURES, "regress_eventlog.jsonl")
    events, skipped = load_events(fixture)
    result = replay_events(events)
    got = format_replay(result, source="FIXTURE", skipped=skipped)
    want = open(os.path.join(FIXTURES, "regress_golden.txt")).read()
    assert got == want
    kinds = [r["kind"] for r in result["regressions"]]
    assert kinds == ["warm_slowdown", "tail_regression", "verdict_flip",
                     "rung_escalation"]
    flip = result["regressions"][2]
    assert (flip["from"], flip["to"]) == ("device", "host")
    slow = result["regressions"][0]
    assert slow["factor"] == pytest.approx(3.49, abs=0.01)


def test_regress_cli_deterministic(capsys):
    from spark_rapids_tpu.tools.regress import main
    fixture = os.path.join(FIXTURES, "regress_eventlog.jsonl")
    assert main([fixture, "--json"]) == 1     # regressions -> rc 1
    out1 = capsys.readouterr().out
    assert main([fixture, "--json"]) == 1
    out2 = capsys.readouterr().out
    assert out1 == out2
    doc = json.loads(out1)
    assert doc["records"] == 12 and doc["skipped"] == 1
    assert len(doc["regressions"]) == 4


def test_regress_bench_diff(tmp_path, capsys):
    from spark_rapids_tpu.tools.regress import (diff_bench,
                                                format_bench_delta,
                                                load_bench, main)
    base = {"geomean": 1.2, "placement_counts": {"device": 2, "host": 1},
            "details": {"q1": {"speedup": 2.0, "placement": "device"},
                        "q6": {"speedup": 1.5, "placement": "device"},
                        "strings": {"speedup": 0.4,
                                    "placement": "host"}}}
    new = {"geomean": 0.9, "placement_counts": {"device": 1, "host": 2},
           "details": {"q1": {"speedup": 0.8, "placement": "host"},
                       "q6": {"speedup": 1.45, "placement": "device"},
                       "q9": {"speedup": 3.0, "placement": "device"}}}
    bp, np_ = str(tmp_path / "BENCH_r01.json"), str(tmp_path
                                                    / "BENCH_r02.json")
    json.dump(base, open(bp, "w"))
    json.dump(new, open(np_, "w"))
    delta = diff_bench(load_bench(bp), load_bench(np_))
    assert delta["regressions"] == [
        {"rung": "q1", "base": 2.0, "now": 0.8, "ratio": 0.4}]
    assert delta["placement_flips"] == [
        {"rung": "q1", "from": "device", "to": "host"}]
    assert delta["only_base"] == ["strings"]
    assert delta["only_new"] == ["q9"]
    line = format_bench_delta(delta, "BENCH_r01.json")
    assert line == (
        "delta vs BENCH_r01.json: geomean 1.200x -> 0.900x, placement "
        "2dev/1host -> 1dev/2host, 1 regressed rung(s), 1 placement "
        "flip(s) over 2 shared rung(s); worst q1 2.0x -> 0.8x; "
        "flip q1 device->host")
    # CLI path: same differ, rc 1 on regression
    assert main(["--bench", bp, np_]) == 1
    assert capsys.readouterr().out.strip() == line
    # the driver-captured wrapper shape loads too
    wp = str(tmp_path / "BENCH_r03.json")
    json.dump({"parsed": new, "tail": ""}, open(wp, "w"))
    assert load_bench(wp)["details"] == load_bench(np_)["details"]
    assert main(["--bench", np_, wp]) == 0    # identical: no regression


# ---------------------------------------------------------------------------
# queryEnd reason/degraded satellite
# ---------------------------------------------------------------------------

def test_query_end_reason_on_timeout(tmp_path, capsys):
    from spark_rapids_tpu.mem.semaphore import QueryTimeout
    elog = str(tmp_path / "elog")
    s = tpu_session({"spark.rapids.tpu.eventLog.enabled": True,
                     "spark.rapids.tpu.eventLog.dir": elog,
                     "spark.rapids.tpu.query.timeout": 0.3})

    def slow(pdf):
        time.sleep(0.25)
        return pdf

    df = (s.create_dataframe(_T, num_partitions=4)
          .map_in_pandas(slow, _T.schema).order_by(F.col("u").asc()))
    with pytest.raises(QueryTimeout):
        df.collect_arrow()
    from spark_rapids_tpu.tools.history import (build_history,
                                                format_history,
                                                load_events)
    events, _ = load_events(elog)
    ends = [e for e in events if e.get("event") == "queryEnd"]
    assert ends and ends[-1]["ok"] is False
    assert ends[-1]["reason"].startswith("QueryTimeout:")
    assert ends[-1]["degraded"] is False
    hist = build_history(events)
    assert hist[-1]["status"] == "failed"
    txt = format_history(hist)
    assert "QueryTimeout" in txt and "reason" in txt.splitlines()[1]


def test_query_end_degraded_reason_on_rung4(tmp_path):
    from spark_rapids_tpu.aux.fault import ChaosController, install_chaos
    elog = str(tmp_path / "elog")
    s = tpu_session({"spark.rapids.tpu.eventLog.enabled": True,
                     "spark.rapids.tpu.eventLog.dir": elog,
                     "spark.rapids.tpu.metrics.enabled": True,
                     "spark.rapids.tpu.metrics.sample.intervalMs": 0})
    df = _agg_df(s)
    df.collect_arrow()                        # healthy first
    install_chaos(ChaosController("mem.oom=*"))
    try:
        df.collect_arrow()
    finally:
        install_chaos(None)
    from spark_rapids_tpu.tools.history import build_history, load_events
    events, _ = load_events(elog)
    ends = [e for e in events if e.get("event") == "queryEnd"]
    last = ends[-1]
    assert last["ok"] is True and last["degraded"] is True
    assert last["reason"].startswith("degraded:")
    assert last["ladderRung"] == 4
    hist = build_history(events)
    assert hist[-1]["status"] == "degraded"
    # clean first run recorded rung 0
    assert ends[0]["ladderRung"] == 0 and ends[0]["degraded"] is False


# ---------------------------------------------------------------------------
# merge_snapshots last_seen satellite
# ---------------------------------------------------------------------------

def test_merge_snapshots_stamps_last_seen():
    from spark_rapids_tpu.metrics.export import (merge_snapshots,
                                                 prometheus_text)
    snaps = {
        "worker-0": {"__ts__": 1000.0, "srtpu_queries_total": {
            "kind": "counter",
            "series": [{"labels": {"status": "ok"}, "value": 4}]}},
        "worker-1": {"__ts__": 1600.5, "srtpu_queries_total": {
            "kind": "counter",
            "series": [{"labels": {"status": "ok"}, "value": 2}]}},
    }
    merged = merge_snapshots(snaps)
    lanes = merged["__lanes__"]
    assert lanes["worker-0"]["last_seen_ms"] == 1000000.0
    assert lanes["worker-1"]["last_seen_ms"] == 1600500.0
    series = merged["srtpu_worker_last_seen_ms"]["series"]
    assert [(s["labels"]["worker"], s["value"]) for s in series] == [
        ("worker-0", 1000000.0), ("worker-1", 1600500.0)]
    txt = prometheus_text(merged)
    assert 'srtpu_worker_last_seen_ms{worker="worker-0"} 1000000.0' \
        in txt
    # a stale lane's counters are still merged but its staleness is now
    # visible in the same exposition
    assert 'srtpu_queries_total{status="ok",worker="worker-0"} 4' in txt


def test_inventory_covers_new_metrics():
    from spark_rapids_tpu.metrics.registry import metric_inventory
    inv = metric_inventory()
    for name, kind in (("srtpu_flight_dumps_total", "counter"),
                       ("srtpu_query_regressions_total", "counter"),
                       ("srtpu_worker_last_seen_ms", "gauge"),
                       ("srtpu_hbm_pressure_grant_bytes", "gauge"),
                       ("srtpu_ops_requests_total", "counter"),
                       ("srtpu_admission_admitted_total", "counter"),
                       ("srtpu_admission_rejected_total", "counter"),
                       ("srtpu_admission_wait_seconds", "histogram"),
                       ("srtpu_admission_queue_depth", "gauge"),
                       ("srtpu_tenant_hbm_used_bytes", "gauge"),
                       ("srtpu_tenant_hbm_quota_bytes", "gauge")):
        assert inv[name]["kind"] == kind, name


# ---------------------------------------------------------------------------
# ISSUE 18: grant-pool hysteresis, tenant/admission rendering, overload
# ---------------------------------------------------------------------------

def test_memory_verdict_clears_after_grant_pool_drains(tmp_path,
                                                       monkeypatch):
    """Satellite regression: a release() arriving AFTER its pressure
    grant's scope exits used to strand bytes in the pool forever, and
    the /healthz memory verdict degraded permanently. The pool must
    drain to zero, and the verdict must clear once the pool has been
    empty past the clear horizon (hysteresis, not an instant flap)."""
    from spark_rapids_tpu.mem.manager import MemoryManager
    from spark_rapids_tpu.ops import server as srv_mod
    mm = MemoryManager(1 << 20, 1 << 30, str(tmp_path / "sp"))
    monkeypatch.setattr(MemoryManager, "_instances",
                        {("grant-clear-test",): mm})
    srv = _start_server()
    with mm.pressure_host_grant():
        mm.reserve(100)                 # lands in the unbudgeted pool
    mm.release(100)                     # arrives AFTER the scope exit
    st = mm.stats()
    assert st["pressure_granted"] == 0, "pool residue leaked"
    assert st["device_used"] == 0
    assert st["pressure_grant_idle_s"] is not None
    # hysteresis: just drained -> the verdict holds degraded...
    doc = json.loads(_get_any(srv.port, "/healthz")[1])
    assert doc["memory"]["verdict"] == "degraded"
    # ...and CLEARS once the pool has been empty past the horizon
    monkeypatch.setattr(srv_mod, "_GRANT_CLEAR_HORIZON_S", 0.05)
    time.sleep(0.06)
    doc = json.loads(_get_any(srv.port, "/healthz")[1])
    assert doc["memory"]["verdict"] == "ok"
    assert doc["memory"]["pressure_grant_idle_s"] >= 0.05


def test_queries_and_history_render_tenant_admission(tmp_path):
    """Satellite: /queries rows and tools/history carry the tenant id
    and the admission outcome; queryEnd records tenant + queuedMs."""
    srv = _start_server()
    elog = str(tmp_path / "elog")
    s = tpu_session({"spark.rapids.tpu.admission.enabled": True,
                     "spark.rapids.tpu.tenant.id": "team-a",
                     "spark.rapids.tpu.eventLog.enabled": True,
                     "spark.rapids.tpu.eventLog.dir": elog})
    _agg_df(s).collect_arrow()
    doc = json.loads(_get(srv.port, "/queries")[1])
    rec = doc["recent"][-1]
    assert rec["tenant"] == "team-a"
    assert rec["admission"] == "admitted"
    assert rec["queuedMs"] >= 0
    from spark_rapids_tpu.tools.history import (build_history,
                                                format_history,
                                                load_events)
    events, _ = load_events(elog)
    ends = [e for e in events if e.get("event") == "queryEnd"]
    assert ends[-1]["tenant"] == "team-a"
    assert ends[-1]["queuedMs"] is not None
    assert ends[-1]["admission"] == "admitted"
    hist = build_history(events)
    assert hist[-1]["tenant"] == "team-a"
    assert hist[-1]["admission"] == "admitted"
    txt = format_history(hist)
    assert "tenant" in txt.splitlines()[1]
    assert "team-a" in txt


def test_overload_sheds_and_ops_plane_stays_responsive(tmp_path,
                                                       monkeypatch):
    """Acceptance (ISSUE 18): overload never wedges the process — with
    every slot held and the queue full, refusals are structured
    (AdmissionRejected + retry-after), the ops endpoints still answer,
    /healthz serves 503 with verdicts, /queries + /healthz list the
    queued/shed state, and admission recovers once the pressure
    clears."""
    from spark_rapids_tpu.mem.manager import MemoryManager
    from spark_rapids_tpu.sched import admission as adm_mod
    srv = _start_server()
    ctl = adm_mod.install_admission(adm_mod.AdmissionController(
        max_in_flight=1, max_queued=1, retry_after_ms=50))
    holder = ctl.admit(tenant="hog", priority=3)
    queued_done = threading.Event()

    def waiter():
        t = ctl.admit(tenant="patient", priority=3)
        ctl.release(t)
        queued_done.set()

    th = threading.Thread(target=waiter)
    th.start()
    deadline = time.monotonic() + 10
    while not ctl.stats()["queued"]:
        assert time.monotonic() < deadline, "waiter never queued"
        time.sleep(0.005)
    # queue full: the next admission is REFUSED, not parked forever
    with pytest.raises(adm_mod.AdmissionRejected) as ei:
        ctl.admit(tenant="burst", priority=3)
    assert ei.value.reason == "queue_full"
    assert ei.value.retry_after_s >= 0.05   # scaled retry-after hint
    # memory pressure on top: /healthz degrades with verdicts while the
    # ops plane stays fully responsive under the overload
    mm = MemoryManager(1 << 20, 1 << 30, str(tmp_path / "sp"))
    monkeypatch.setattr(MemoryManager, "_instances",
                        {("overload-test",): mm})
    mm.reserve_granted(1)
    try:
        code, body = _get_any(srv.port, "/healthz")
        doc = json.loads(body)
        assert code == 503 and doc["status"] == "degraded"
        assert doc["memory"]["verdict"] == "degraded"
        adm = doc["admission"]
        assert adm["enabled"] and adm["shedActive"]
        assert adm["verdict"] == "degraded"
        assert "pressure-grant" in adm["shedReason"]
        assert adm["inFlight"] == 1
        assert [q["tenant"] for q in adm["queued"]] == ["patient"]
        # low-priority admissions are shed with the pressured section
        with pytest.raises(adm_mod.AdmissionRejected) as ei2:
            ctl.admit(tenant="batch", priority=1)
        assert ei2.value.reason == "shed"
        assert ei2.value.tenant == "batch"
    finally:
        mm.release_granted(1)
    # pressure gone, holder releases: the queued ticket admits — the
    # overload degraded service, it never wedged it
    ctl.release(holder)
    assert queued_done.wait(10), "queued admission wedged"
    th.join(timeout=5)
    st = ctl.stats()
    assert st["inFlight"] == 0 and st["queued"] == []
    assert st["rejected"]["queue_full"] == 1
    assert st["rejected"]["shed"] == 1
