"""Placement observability (ISSUE 7): coded not-on-device reasons,
``explain("placement")``, the fallback metric family, event-log
placement summaries, and the qualification CLI.

Covers the closed reason-code registry (unknown codes raise), the
golden ``explain("placement")`` rendering, code stability across the
fused (WholeStageExec) and unfused paths, the
``srtpu_placement_fallback_total`` increments, whole-plan reversions
preserving per-node tags (the wrapping-tag satellite), and the qualify
CLI's determinism + crash-truncated-line tolerance on the checked-in
fixture."""
import json
import os
import re

import numpy as np
import pyarrow as pa
import pytest

from harness import tpu_session
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.plan import tags as T

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
QUALIFY_FIXTURE = os.path.join(FIXTURES, "qualify_eventlog.jsonl")


def _table(n=200):
    return pa.table({
        "k": pa.array(np.arange(n) % 7),
        "v": pa.array(np.arange(n, dtype=np.float64)),
        "j": pa.array(['{"a": "1"}'] * n),
    })


def _host_filter_query(s):
    """Filter whose condition is intrinsically host-only (JSON parse)."""
    return (s.create_dataframe(_table())
            .filter(F.get_json_object(F.col("j"), "$.a") == F.lit("1"))
            .group_by("k").agg(F.sum(F.col("v")).with_name("sv")))


# ---------------------------------------------------------------------------
# the closed registry
# ---------------------------------------------------------------------------

def test_unknown_code_rejected():
    with pytest.raises(ValueError):
        T.make_tag("NOT_A_REGISTERED_CODE", "detail")
    # the meta tagging path funnels through make_tag: same guarantee
    from spark_rapids_tpu.config import TpuConf
    from spark_rapids_tpu.plan.logical import LogicalScan
    from spark_rapids_tpu.plan.meta import PlanMeta
    from spark_rapids_tpu.types import Schema
    m = PlanMeta(LogicalScan([], Schema([])), TpuConf(), None)
    with pytest.raises(ValueError):
        m.will_not_work_on_tpu("some reason", code="UNKNOWN")
    assert m.can_run_on_tpu in (True, False)   # no partial state left
    assert m.tags == [] and m.reasons == []


def test_every_code_documented():
    """docs/placement.md mirrors the closed registry (both directions)."""
    with open(os.path.join(os.path.dirname(FIXTURES), "..",
                           "docs", "placement.md")) as f:
        doc = f.read()
    for code in T.REASON_CODES:
        assert f"`{code}`" in doc, f"{code} missing from docs/placement.md"


# ---------------------------------------------------------------------------
# explain("placement")
# ---------------------------------------------------------------------------

def test_explain_placement_golden(capsys):
    s = tpu_session({"spark.rapids.tpu.sql.exec.Sort": False})
    df = _host_filter_query(s).order_by("k")
    out = df.explain("placement")
    capsys.readouterr()
    with open(os.path.join(FIXTURES, "placement_golden.txt")) as f:
        assert out + "\n" == f.read()


def test_explain_placement_never_executes():
    s = tpu_session()
    df = _host_filter_query(s)
    df.explain("placement")
    assert s.last_query_metrics is None     # plans only


def test_placement_explain_conf_logs_report(caplog):
    import logging
    s = tpu_session({"spark.rapids.tpu.explain": "NOT_ON_DEVICE"})
    with caplog.at_level(logging.WARNING,
                         logger="spark_rapids_tpu.overrides"):
        _host_filter_query(s).collect_arrow()
    txt = "\n".join(r.getMessage() for r in caplog.records)
    assert "[EXPR_UNSUPPORTED]" in txt
    assert "placement verdict:" in txt
    # NOT_ON_DEVICE hides clean device rows
    assert "on device" not in txt
    # ... and stays SILENT for an all-device plan (nothing on host,
    # nothing to say — the legacy NOT_ON_TPU contract)
    caplog.clear()
    with caplog.at_level(logging.WARNING,
                         logger="spark_rapids_tpu.overrides"):
        (s.create_dataframe(_table()).group_by("k")
         .agg(F.sum(F.col("v")).with_name("sv")).collect_arrow())
    assert not [r for r in caplog.records
                if "placement verdict" in r.getMessage()]


# ---------------------------------------------------------------------------
# report semantics
# ---------------------------------------------------------------------------

def _device_chain(s):
    return (s.create_dataframe(_table())
            .filter(F.col("v") > 10.0)
            .with_column("w", F.col("v") * F.lit(2.0))
            .filter(F.col("w") < 300.0)
            .drop("j"))


def test_codes_stable_fused_vs_unfused():
    """The report is built from the tagged meta tree, so whole-stage
    fusion (PR 6) must not change a single code."""
    summaries, trees = [], []
    for fusion in (True, False):
        s = tpu_session({"spark.rapids.tpu.fusion.enabled": fusion})
        df = _device_chain(s)
        physical = df._physical()
        summaries.append(physical.placement_report.summary())
        trees.append(physical.tree_string())
    assert summaries[0] == summaries[1]
    assert "WholeStage" in trees[0] and "WholeStage" not in trees[1]
    assert summaries[0]["verdict"] == "device"


def test_summary_shape_and_session_surface():
    s = tpu_session()
    df = _host_filter_query(s)
    df.collect_arrow()
    got = s.last_placement_report
    assert got is not None
    assert set(got) == {"verdict", "codes", "ops", "estRows"}
    assert got["codes"].get("EXPR_UNSUPPORTED") == 1
    assert "Filter" in got["ops"]
    assert got["estRows"] and got["estRows"] > 0
    # cleared on entry: a query failing before execution leaves None
    s2 = s.set_conf("spark.rapids.tpu.sql.mode", "explainOnly")
    with pytest.raises(RuntimeError):
        df.collect_arrow()
    assert s2.last_placement_report is not None  # planning succeeded
    assert s2.last_placement_report["codes"]


def test_whole_plan_revert_preserves_node_tags():
    """Satellite: a whole-plan host reversion must not clobber a node's
    own recorded reasons — it nests as a wrapping plan-level tag."""
    s = tpu_session({"spark.rapids.tpu.sql.optimizer.enabled": True})
    physical = _host_filter_query(s)._physical()
    rep = physical.placement_report
    assert rep.verdict == "host"
    codes = rep.counts()
    assert codes.get("EXPR_UNSUPPORTED") == 1
    assert codes.get("WHOLE_PLAN_HOST_REVERT", 0) >= 1
    # the Filter keeps ONLY its original cause
    ops = rep.summary()["ops"]
    assert ops["Filter"] == {"EXPR_UNSUPPORTED": 1}
    # and the reversion renders as the wrapping reason
    txt = rep.render()
    assert "(wraps the whole plan)" in txt
    assert "[EXPR_UNSUPPORTED]" in txt


def test_explain_analyze_carries_verdict():
    s = tpu_session()
    out = _host_filter_query(s)._explain_analyze()
    assert out.startswith("placement fallbacks [device]: "
                          "EXPR_UNSUPPORTED x1\n"), out


def test_trace_query_span_carries_verdict(tmp_path):
    out = str(tmp_path / "trace.json")
    s = tpu_session({"spark.rapids.tpu.trace.enabled": True,
                     "spark.rapids.tpu.trace.output": out})
    _host_filter_query(s).collect_arrow()
    with open(out) as f:
        doc = json.load(f)
    events = doc if isinstance(doc, list) else doc.get("traceEvents", [])
    q = [e for e in events if e.get("name") == "query"]
    assert q, "no query span in the trace artifact"
    assert q[-1].get("args", {}).get("placement") == "device"


# ---------------------------------------------------------------------------
# metric family
# ---------------------------------------------------------------------------

def test_fallback_metric_increments():
    from spark_rapids_tpu.metrics import (active_registry,
                                          registry_snapshot,
                                          shutdown_metrics)
    s = tpu_session({"spark.rapids.tpu.metrics.enabled": True,
                     "spark.rapids.tpu.metrics.sample.intervalMs": 0})
    _host_filter_query(s).collect_arrow()
    _host_filter_query(s).collect_arrow()
    snap = registry_snapshot(active_registry())
    series = snap["srtpu_placement_fallback_total"]["series"]
    got = {(se["labels"]["op"], se["labels"]["code"]): se["value"]
           for se in series}
    assert got[("Filter", "EXPR_UNSUPPORTED")] == 2
    shutdown_metrics()


# ---------------------------------------------------------------------------
# event log + qualify CLI
# ---------------------------------------------------------------------------

def test_event_log_carries_placement_and_qualify_is_deterministic(
        tmp_path, capsys):
    from spark_rapids_tpu.tools.history import load_events
    from spark_rapids_tpu.tools.qualify import main
    d = str(tmp_path / "elog")
    s = tpu_session({"spark.rapids.tpu.eventLog.enabled": True,
                     "spark.rapids.tpu.eventLog.dir": d})
    _host_filter_query(s).collect_arrow()
    (s.create_dataframe(_table()).group_by("k")
     .agg(F.sum(F.col("v")).with_name("sv")).collect_arrow())
    events, _ = load_events(d)
    starts = [e for e in events if e.get("event") == "queryStart"]
    assert len(starts) == 2
    assert starts[0]["placement"]["codes"] == {"EXPR_UNSUPPORTED": 1}
    assert starts[1]["placement"]["codes"] == {}
    assert main([d]) == 0
    out1 = capsys.readouterr().out
    assert main([d]) == 0
    out2 = capsys.readouterr().out
    assert out1 == out2
    assert "EXPR_UNSUPPORTED" in out1
    assert main([d, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["codes"][0]["code"] == "EXPR_UNSUPPORTED"
    assert rep["skipped_lines"] == 0


def test_qualify_golden_fixture(capsys, monkeypatch):
    """Deterministic ranked report over the checked-in event log; the
    fixture embeds a crash-truncated trailing line (skipped, counted)
    and q9/q28-shaped host reverts whose dominant cause ranks first."""
    from spark_rapids_tpu.tools import qualify
    from spark_rapids_tpu.tools.qualify import main
    # hermetic cost basis: earlier tests in the same process may have
    # taught the live cost model trusted fused-stage walls, which the
    # CLI would (correctly) prefer over the speedup priors the golden
    # was generated with
    monkeypatch.setattr(qualify, "_learned_device_cost", lambda: None)
    assert main([QUALIFY_FIXTURE]) == 0
    out = capsys.readouterr().out
    with open(os.path.join(FIXTURES, "qualify_golden.txt")) as f:
        assert out == f.read()
    assert "1 undecodable line(s) skipped" in out
    # the dominant host cause of the multi-agg q9/q28 shapes tops the list
    first_row = out.splitlines()[5]
    assert re.match(r"\s*1\s+WHOLE_PLAN_HOST_REVERT\b", first_row), first_row


def test_qualify_truncated_lines_never_fatal(tmp_path, capsys,
                                             monkeypatch):
    from spark_rapids_tpu.tools import qualify
    from spark_rapids_tpu.tools.qualify import analyze
    monkeypatch.setattr(qualify, "_learned_device_cost", lambda: None)
    p = tmp_path / "events.jsonl"
    with open(QUALIFY_FIXTURE) as f:
        content = f.read()
    # the fixture's own trailing line is itself crash-truncated (no
    # newline); add a second truncated record after it
    p.write_text(content + '\n{"event": "queryEnd", "que')
    rep = analyze(str(p))
    assert rep["skipped_lines"] == 2            # fixture's + ours
    assert rep["codes"][0]["code"] == "WHOLE_PLAN_HOST_REVERT"


def test_qualify_crashed_start_never_clobbers_completed_run(tmp_path):
    """A stale queryStart with no end (crash) must not overwrite the
    placement summary of a later COMPLETED run of the same digest, and
    per-session queryIds must not collide across sessions sharing a
    log directory."""
    from spark_rapids_tpu.tools.qualify import analyze
    p = tmp_path / "events.jsonl"
    host_pl = {"verdict": "host", "codes": {"EXPR_UNSUPPORTED": 1},
               "ops": {"Filter": {"EXPR_UNSUPPORTED": 1}}, "estRows": 10}
    dev_pl = {"verdict": "device", "codes": {}, "ops": {}, "estRows": 10}
    recs = [
        # session A: digest X crashes mid-query while host-placed
        {"event": "queryStart", "queryId": 1, "planDigest": "X",
         "placement": host_pl},
        # session B reuses queryId 1 for a DIFFERENT digest Y
        {"event": "queryStart", "queryId": 1, "planDigest": "Y",
         "placement": dev_pl},
        {"event": "queryEnd", "queryId": 1, "planDigest": "Y", "ok": True,
         "durationMs": 5.0},
        # digest X later completes on device (e.g. after a conf fix)
        {"event": "queryStart", "queryId": 2, "planDigest": "X",
         "placement": dev_pl},
        {"event": "queryEnd", "queryId": 2, "planDigest": "X", "ok": True,
         "durationMs": 7.0},
        # digest Z only ever crashes: its LATEST start's summary wins
        {"event": "queryStart", "queryId": 3, "planDigest": "Z",
         "placement": {"verdict": "host", "codes": {"CONF_DISABLED": 2},
                       "ops": {"Sort": {"CONF_DISABLED": 2}},
                       "estRows": 10}},
        {"event": "queryStart", "queryId": 4, "planDigest": "Z",
         "placement": host_pl},
    ]
    p.write_text("".join(json.dumps(r) + "\n" for r in recs))
    rep = analyze(str(p))
    # neither the crashed start nor the cross-session qid collision
    # resurrects the obsolete host placement of X/Y...
    assert rep["host_placed"] == 1          # only crash-only digest Z
    # ...and Z reports its freshest crash summary, not the first one
    assert [e["code"] for e in rep["codes"]] == ["EXPR_UNSUPPORTED"]


def test_qualify_uses_learned_device_cost(monkeypatch):
    """With a trusted learned device row cost the estimate switches
    from the speedup priors to measurement-based pricing (the fused
    WholeStageExec cost covers records with no kind-specific entry)."""
    from spark_rapids_tpu.plan import cost
    from spark_rapids_tpu.tools.qualify import analyze
    monkeypatch.setattr(cost, "_OP_COSTS",
                        {("WholeStageExec", "device"): (10_000_000, 1.0)})
    rep = analyze(QUALIFY_FIXTURE)
    assert rep["learned_device_cost"]["WholeStageExec"] \
        == pytest.approx(1e-7)
    top = rep["codes"][0]
    assert top["code"] == "WHOLE_PLAN_HOST_REVERT"
    assert top["est_saved_ms"] > 0


def test_qualify_prefers_per_operator_learned_costs(monkeypatch):
    """Records whose operators have kind-specific learned costs price
    the device wall from the SUM of those costs, not the fused-region
    fallback: a deliberately huge Filter cost must shrink the estimated
    saving of Filter-tagged records versus the fused-only basis."""
    from spark_rapids_tpu.plan import cost
    from spark_rapids_tpu.tools.qualify import analyze
    monkeypatch.setattr(cost, "_OP_COSTS",
                        {("WholeStageExec", "device"): (10_000_000, 1.0)})
    cheap = analyze(QUALIFY_FIXTURE)
    monkeypatch.setattr(cost, "_OP_COSTS", {
        ("WholeStageExec", "device"): (10_000_000, 1.0),
        ("Filter", "device"): (10_000_000, 10_000.0),  # 1e-3 s/row: huge
        ("Aggregate", "device"): (10_000_000, 10_000.0),
    })
    pricey = analyze(QUALIFY_FIXTURE)
    assert set(pricey["learned_device_cost"]) == {
        "Aggregate", "Filter", "WholeStageExec"}

    def saved(rep, code):
        return {e["code"]: e["est_saved_ms"]
                for e in rep["codes"]}.get(code, 0.0)
    # every fixture record carries Filter/Aggregate ops: the per-op
    # pricing makes the device look expensive -> savings collapse
    assert saved(pricey, "WHOLE_PLAN_HOST_REVERT") \
        < saved(cheap, "WHOLE_PLAN_HOST_REVERT")
