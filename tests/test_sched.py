"""Admission-controller and tenant-quota unit tests (ISSUE 18).

Covers the sched/admission.py scheduler in isolation — priority order,
FIFO-within-class, queue_full/deadline/shed/chaos refusals, aging
(starvation-proofing, the satellite-4 fairness bar), leak-free reject
paths, idempotent release — plus the MemoryManager per-tenant HBM quota
(census attribution, own-ladder breach, zero cross-tenant spill) and
the wedge-watchdog interaction with a queued admission.
"""
import threading
import time

import pytest

from spark_rapids_tpu.mem.manager import (MemoryManager, RetryOOM,
                                          SplitAndRetryOOM)
from spark_rapids_tpu.sched.admission import (AdmissionController,
                                              AdmissionRejected,
                                              shed_reason)


def _ctl(**kw):
    args = dict(max_in_flight=1, max_queued=8, aging_ms=1000,
                retry_after_ms=100)
    args.update(kw)
    return AdmissionController(**args)


def _mm(budget=1 << 20):
    return MemoryManager(budget, 1 << 30, "/tmp/srtpu_sched_test")


# ---------------------------------------------------------------------------
# admit / release basics
# ---------------------------------------------------------------------------

def test_fast_path_admit_release():
    ctl = _ctl(max_in_flight=2)
    a = ctl.admit(tenant="a")
    b = ctl.admit(tenant="b")
    assert a.admitted and b.admitted
    st = ctl.stats()
    assert st["inFlight"] == 2 and st["queued"] == []
    ctl.release(a)
    ctl.release(b)
    assert ctl.stats()["inFlight"] == 0
    assert ctl.stats()["admitted"] == 2


def test_release_idempotent_and_never_admitted_noop():
    ctl = _ctl()
    t = ctl.admit()
    ctl.release(t)
    ctl.release(t)                      # double release: no underflow
    assert ctl.stats()["inFlight"] == 0
    from spark_rapids_tpu.sched.admission import AdmissionTicket
    ghost = AdmissionTicket("x", 1, 999, None)
    ctl.release(ghost)                  # never admitted: no-op
    assert ctl.stats()["inFlight"] == 0


def test_queue_full_rejection_scales_retry_after():
    ctl = _ctl(max_in_flight=1, max_queued=0, retry_after_ms=100)
    a = ctl.admit()
    with pytest.raises(AdmissionRejected) as ei:
        ctl.admit()
    e = ei.value
    assert e.reason == "queue_full"
    assert e.retry_after_s >= 0.1
    assert "retry after" in str(e)
    ctl.release(a)
    # slot free again: admission recovers without any external help
    b = ctl.admit()
    assert b.admitted
    ctl.release(b)
    assert ctl.stats()["rejected"] == {"queue_full": 1}


def test_priority_order_and_fifo_within_class():
    """Three queued tickets: the freed slot goes to the highest priority
    first; equal priorities drain FIFO."""
    ctl = _ctl(max_in_flight=1, aging_ms=0)   # no aging: pure priority
    gate = ctl.admit()
    order = []
    # enqueue one at a time (each gated, confirmed queued via stats)
    # so the arrival order — and therefore FIFO seq — is deterministic
    seq_gate = [threading.Event() for _ in range(3)]

    def enq(i, name, prio):
        seq_gate[i].wait(10)
        t = ctl.admit(tenant=name, priority=prio)
        order.append(name)
        ctl.release(t)

    specs = [("low-first", 1), ("high", 5), ("low-second", 1)]
    threads = [threading.Thread(target=enq, args=(i, n, p))
               for i, (n, p) in enumerate(specs)]
    for th in threads:
        th.start()
    for i in range(3):
        seq_gate[i].set()
        # wait until that ticket is visibly queued before the next
        deadline = time.monotonic() + 10
        while len(ctl.stats()["queued"]) < i + 1:
            assert time.monotonic() < deadline, "ticket never queued"
            time.sleep(0.005)
    ctl.release(gate)                   # open the floodgate
    for th in threads:
        th.join(timeout=10)
        assert not th.is_alive()
    assert order == ["high", "low-first", "low-second"]


def test_aging_promotes_starved_low_priority():
    """Satellite 4 (fairness): a continuous stream of high-priority
    admissions cannot starve a queued low-priority ticket — aging lifts
    its effective priority one class per agingMs until it wins."""
    ctl = _ctl(max_in_flight=1, aging_ms=50)   # ages fast for the test
    first = ctl.admit(tenant="hog", priority=5)
    low_done = threading.Event()

    def low():
        t = ctl.admit(tenant="batch", priority=1)
        low_done.set()
        ctl.release(t)

    lo = threading.Thread(target=low)
    lo.start()
    deadline = time.monotonic() + 10
    while not ctl.stats()["queued"]:
        assert time.monotonic() < deadline
        time.sleep(0.005)
    # keep a high-priority stream arriving while the low waits; each
    # holds the slot briefly then releases — without aging the fresh
    # priority-5 would win every wakeup
    ctl.release(first)
    t_end = time.monotonic() + 5.0
    while not low_done.is_set() and time.monotonic() < t_end:
        try:
            t = ctl.admit(tenant="hog", priority=5)
        except AdmissionRejected:
            time.sleep(0.01)
            continue
        time.sleep(0.01)
        ctl.release(t)
    assert low_done.is_set(), \
        "aging failed: low-priority ticket starved by priority-5 stream"
    lo.join(timeout=5)
    # the starved ticket's effective priority visibly aged in stats
    st = ctl.stats()
    assert st["queued"] == [] and st["inFlight"] in (0, 1)


def test_deadline_rejected_up_front_and_in_queue():
    ctl = _ctl(max_in_flight=1)
    # already-expired deadline refuses immediately, even with free slots
    with pytest.raises(AdmissionRejected) as ei:
        ctl.admit(deadline=time.monotonic() - 0.1)
    assert ei.value.reason == "deadline"
    # a queued ticket whose deadline expires while waiting is refused
    # on wake and leaves no queue residue
    hold = ctl.admit()
    with pytest.raises(AdmissionRejected) as ei:
        ctl.admit(deadline=time.monotonic() + 0.15)
    assert ei.value.reason == "deadline"
    assert ctl.stats()["queued"] == []   # leak-free reject path
    ctl.release(hold)
    t = ctl.admit(deadline=time.monotonic() + 30)
    assert t.admitted
    ctl.release(t)


def test_deadline_estimator_refuses_unmeetable_wait():
    """With a hold-time EWMA learned from real admissions, a deadline
    shorter than the estimated queue wait is refused up front."""
    ctl = _ctl(max_in_flight=1)
    # teach the EWMA a ~0.2s hold
    t = ctl.admit()
    time.sleep(0.2)
    ctl.release(t)
    assert ctl.stats()["holdEwmaS"] > 0.1
    hold = ctl.admit()                   # slot busy -> one wave ahead
    with pytest.raises(AdmissionRejected) as ei:
        ctl.admit(deadline=time.monotonic() + 0.01)
    assert ei.value.reason == "deadline"
    assert "estimated queue wait" in str(ei.value)
    ctl.release(hold)


# ---------------------------------------------------------------------------
# shedding
# ---------------------------------------------------------------------------

def test_shed_reason_reads_healthz_conditions(monkeypatch):
    """shed_reason reads the same process-wide accounting /healthz does
    (stats_all over registered instances), so the mm must be in the
    singleton table like a session's manager would be."""
    mm = _mm()
    key = ("test-shed-reason",)
    MemoryManager._instances[key] = mm
    try:
        mm.reserve_granted(4096)        # pressure pool nonzero
        r = shed_reason()
        assert r is not None and "pressure-grant" in r
        mm.release_granted(4096)
        # hysteresis: a just-drained pool sheds until the clear horizon
        r = shed_reason()
        assert r is not None and "drained only" in r
        from spark_rapids_tpu.ops import server as srv_mod
        monkeypatch.setattr(srv_mod, "_GRANT_CLEAR_HORIZON_S", 0.0)
        r = shed_reason()
        # horizon zeroed: the grant pool no longer sheds (under full-
        # suite ordering OTHER leftover degraded state may still)
        assert r is None or "pressure-grant" not in r
    finally:
        MemoryManager._instances.pop(key, None)


def test_shed_refuses_below_floor_and_admits_above(monkeypatch):
    import spark_rapids_tpu.sched.admission as adm_mod
    monkeypatch.setattr(adm_mod, "shed_reason",
                        lambda: "memory: synthetic pressure")
    ctl = _ctl(max_in_flight=4, shed_priority_floor=2)
    with pytest.raises(AdmissionRejected) as ei:
        ctl.admit(tenant="batch", priority=1)
    assert ei.value.reason == "shed"
    assert "synthetic pressure" in str(ei.value)
    assert ei.value.retry_after_s > 0
    t = ctl.admit(tenant="interactive", priority=2)   # at the floor
    assert t.admitted
    ctl.release(t)


def test_shed_burst_fires_flight_trigger(tmp_path, monkeypatch):
    """Satellite 3: a rejection burst past shed.burst inside
    shed.windowMs dumps ONE admission_shed bundle naming the pressured
    section."""
    from spark_rapids_tpu.ops import flight as fl_mod
    import spark_rapids_tpu.sched.admission as adm_mod
    rec = fl_mod.install_flight(fl_mod.FlightRecorder(
        str(tmp_path / "flight"), rate_limit_ms=60000))
    monkeypatch.setattr(adm_mod, "shed_reason",
                        lambda: "memory: pressure-grant pool active")
    ctl = _ctl(max_in_flight=1, shed_burst=4, shed_window_ms=60000)
    for _ in range(4):
        with pytest.raises(AdmissionRejected):
            ctl.admit(tenant="batch", priority=1)
    st = rec.stats()
    assert st["dumps"].get("admission_shed") == 1
    import json
    import os
    bundle = st["bundles"][-1]
    placement = json.load(open(os.path.join(bundle, "placement.json")))
    assert placement["trigger"] == "admission_shed"
    assert "pressure-grant pool active" in placement["detail"]


# ---------------------------------------------------------------------------
# chaos sites
# ---------------------------------------------------------------------------

def test_chaos_admit_reject_and_delay():
    from spark_rapids_tpu.aux.fault import ChaosController, install_chaos
    ctl = _ctl(max_in_flight=4)
    install_chaos(ChaosController("admit.reject=2;admit.delay=1",
                                  delay_ms=30))
    try:
        t0 = time.monotonic()
        a = ctl.admit()                  # hit 1: delayed, not rejected
        assert time.monotonic() - t0 >= 0.025
        with pytest.raises(AdmissionRejected) as ei:
            ctl.admit()                  # hit 2 of admit.reject fires
        assert ei.value.reason == "chaos"
        b = ctl.admit()                  # hit 3: clean again
        ctl.release(a)
        ctl.release(b)
    finally:
        install_chaos(None)
    assert ctl.stats()["rejected"] == {"chaos": 1}
    assert ctl.stats()["inFlight"] == 0


# ---------------------------------------------------------------------------
# tenant HBM quotas (mem/manager.py)
# ---------------------------------------------------------------------------

class _FakeSpillable:
    """Minimal registered buffer: device-resident until spilled. Like
    the real SpillableBatch, it reserves BEFORE registering — the quota
    census must never see a buffer whose bytes are not accounted yet.
    ``pinned`` models a buffer in active use that refuses to spill."""

    def __init__(self, mm, nbytes, pinned=False):
        self.mm = mm
        self.nbytes = nbytes
        self.tier = "device"
        self.spill_priority = 0
        self.pinned = pinned
        mm.reserve(nbytes)
        self.handle = mm.register_spillable(self)

    def device_bytes(self):
        return self.nbytes if self.tier == "device" else 0

    def spill_to_host(self):
        if self.tier != "device" or self.pinned:
            return 0
        self.tier = "host"
        self.mm.release(self.nbytes)
        return self.nbytes

    def close(self):
        if self.tier == "device":
            self.mm.release(self.nbytes)
        self.mm.unregister_spillable(self.handle)


def test_tenant_quota_census_and_self_spill():
    mm = _mm(budget=1000)
    mm.set_thread_tenant("A", quota_bytes=300)
    a1 = _FakeSpillable(mm, 200)
    assert mm.tenant_device_used("A") == 200
    # next reserve would breach: the tenant's OWN buffer spills first,
    # and the reserve then succeeds without raising
    a2 = _FakeSpillable(mm, 250)
    assert a1.tier == "host", "own-tenant spill did not run"
    assert mm.tenant_device_used("A") == 250
    st = mm.stats()
    assert st["tenant_used"]["A"] == 250
    assert st["tenant_quota"]["A"] == 300
    a2.close()
    a1.close()
    mm.set_thread_tenant(None)
    assert mm.audit_leaks() == []


def test_tenant_quota_breach_rides_own_ladder_not_rung3():
    """A quota breach raises RetryOOM (rung 1) after self-spill fails to
    make room — never spilling ANOTHER tenant's buffers."""
    mm = _mm(budget=10000)
    mm.set_thread_tenant("B", quota_bytes=1000)
    b_buf = _FakeSpillable(mm, 900)
    mm.set_thread_tenant("A", quota_bytes=500)
    a_buf = _FakeSpillable(mm, 400, pinned=True)
    # A is at 400/500 and its only buffer is pinned (in active use): a
    # 200-byte reserve breaches with no self-help left, so A's own
    # ladder gets RetryOOM...
    with pytest.raises(RetryOOM) as ei:
        mm.reserve(200)
    assert "tenant A" in str(ei.value)
    # ...while B's buffer NEVER moved (no cross-tenant spill)
    assert a_buf.tier == "device" and b_buf.tier == "device"
    assert mm.tenant_device_used("B") == 900
    # a single allocation larger than the whole share splits (rung 2)
    with pytest.raises(SplitAndRetryOOM):
        mm.reserve(600)
    a_buf.close()
    mm.set_thread_tenant("B", quota_bytes=1000)
    b_buf.close()
    mm.set_thread_tenant(None)
    assert mm.audit_leaks() == []


def test_tenant_quota_disabled_paths():
    mm = _mm(budget=1000)
    # no tenant: quota gate is a no-op
    mm.reserve(800)
    mm.release(800)
    # tenant without quota: attribution only, no enforcement
    mm.set_thread_tenant("C")
    c = _FakeSpillable(mm, 900)
    assert mm.tenant_device_used("C") == 900
    assert "C" not in mm.stats()["tenant_quota"]
    c.close()
    mm.set_thread_tenant(None)


# ---------------------------------------------------------------------------
# satellite 4: wedge watchdog x queued admission
# ---------------------------------------------------------------------------

def test_wedged_semaphore_sheds_queued_admission():
    """A dead semaphore holder degrades the wedge census; a NEW
    low-priority admission is shed (naming the semaphore section) while
    a high-priority one still passes, and after the watchdog reclaims
    the permit admission recovers for everyone."""
    from spark_rapids_tpu.mem.semaphore import (DeviceSemaphore,
                                                wedged_census)
    sem = DeviceSemaphore(2, timeout_s=30.0, wedge_timeout_ms=200)
    th = threading.Thread(target=sem.acquire, name="killed-holder")
    th.start()
    th.join()
    assert wedged_census()["dead"] >= 1
    ctl = _ctl(max_in_flight=2, shed_priority_floor=2)
    with pytest.raises(AdmissionRejected) as ei:
        ctl.admit(tenant="batch", priority=1)
    assert ei.value.reason == "shed" and "semaphore" in str(ei.value)
    hi = ctl.admit(tenant="interactive", priority=3)
    assert hi.admitted
    ctl.release(hi)
    # watchdog reclaims the dead holder's permit -> shed clears
    sem.check_wedged()
    assert wedged_census()["dead"] == 0
    lo = ctl.admit(tenant="batch", priority=1)
    assert lo.admitted
    ctl.release(lo)


# ---------------------------------------------------------------------------
# install plumbing
# ---------------------------------------------------------------------------

def test_conf_gated_install_and_default_width():
    from spark_rapids_tpu.config import TpuConf
    import spark_rapids_tpu.sched.admission as adm_mod
    assert adm_mod.CONTROLLER is None
    adm_mod.ensure_admission_from_conf(TpuConf({}))
    assert adm_mod.CONTROLLER is None        # off by default
    conf = TpuConf({"spark.rapids.tpu.admission.enabled": True,
                    "spark.rapids.tpu.admission.maxQueued": 7})
    ctl = adm_mod.ensure_admission_from_conf(conf)
    assert ctl is adm_mod.CONTROLLER is not None
    # maxInFlight=0 falls back to concurrentTpuTasks
    from spark_rapids_tpu.config import CONCURRENT_TPU_TASKS
    assert ctl.max_in_flight == int(conf.get(CONCURRENT_TPU_TASKS))
    assert ctl.max_queued == 7
    # install-once: a second enabled conf reuses the controller
    ctl2 = adm_mod.ensure_admission_from_conf(
        TpuConf({"spark.rapids.tpu.admission.enabled": True,
                 "spark.rapids.tpu.admission.maxQueued": 99}))
    assert ctl2 is ctl and ctl.max_queued == 7
