"""Sustained mixed-tenant serving load test (ISSUE 18 tentpole bar).

Four tenant sessions — distinct priorities and HBM shares — drive a
Zipf-repeated query mix CONCURRENTLY through one shared MemoryManager,
one shared DeviceSemaphore and one admission controller, while:

* the chaos controller injects ``mem.oom`` at the reserve sites,
* a semaphore holder thread is killed mid-run (the wedge watchdog must
  reclaim its permit),
* a pressure burst (nonzero grant pool) forces the controller to SHED
  low-priority admissions, which must recover once the pool drains.

Acceptance asserted here (the ISSUE 18 bar):

* every admitted query's result is byte-identical to the fault-free
  baseline;
* admission latency is bounded (p99 over the event-logged queuedMs);
* the per-tenant quota census never attributes bytes across tenants
  and drains to zero (plus the suite-wide zero-leak audit);
* shed admissions carry a retry-after hint and succeed on retry after
  the pressure clears;
* the run is recorded as a BENCH-style ``SERVE_r02.json`` artifact
  that ``tools/regress.load_bench`` parses (per-tenant throughput as
  the speedup column), now carrying sketch-derived per-tenant
  p50/p95/p99 latencies (ISSUE 20): every event-logged duration folds
  through the SAME ``QuantileSketch`` the live ``Summary`` metric kind
  and the ``/slo`` endpoint use.
"""
import json
import os
import threading
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from harness import tpu_session
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.aux.fault import ChaosController, install_chaos
from spark_rapids_tpu.exec.base import ExecContext
from spark_rapids_tpu.mem import DeviceSemaphore, MemoryManager

pytestmark = pytest.mark.chaos

_RNG = np.random.RandomState(18)
_N = 4096
#: integer-only: every result compares EXACTLY across engines/rungs
_T = pa.table({
    "k": pa.array(_RNG.randint(0, 17, _N)),
    "g": pa.array(_RNG.randint(0, 5, _N)),
    "v": pa.array(_RNG.randint(0, 1000, _N).astype(np.int64)),
    "u": pa.array(np.arange(_N)),
})

#: (tenant id, admission priority, HBM share)
_TENANTS = [("alpha", 3, 0.5), ("beta", 2, 0.5),
            ("gamma", 1, 0.5), ("delta", 1, 0.5)]

#: Zipf-ish repetition over the query shapes: shape 0 dominates, the
#: tail shapes recur rarely — the serving access pattern the exec cache
#: and the admission hold-time EWMA both see in practice
_ZIPF_MIX = [0, 0, 0, 0, 1, 1, 2]


def _mk_session(mm, sem, tenant, priority, share, elog_dir):
    conf = {"spark.rapids.tpu.admission.enabled": True,
            "spark.rapids.tpu.admission.maxInFlight": 2,
            "spark.rapids.tpu.admission.maxQueued": 16,
            "spark.rapids.tpu.tenant.id": tenant,
            "spark.rapids.tpu.tenant.priority": priority,
            "spark.rapids.tpu.tenant.hbmShare": share,
            "spark.rapids.tpu.eventLog.enabled": True,
            "spark.rapids.tpu.eventLog.dir": elog_dir,
            "spark.rapids.tpu.semaphore.wedgeTimeoutMs": 300,
            # pin the memory-managed operator pipeline (the fused/
            # distributed paths have their own memory story and skip
            # the reserve sites this battery pressures)
            "spark.rapids.tpu.distributed.enabled": False,
            "spark.rapids.tpu.sql.fusedPipeline.enabled": False}
    s = tpu_session(conf)
    s._ctx = ExecContext(s.conf, semaphore=sem, memory=mm)
    return s


def _shapes(s):
    agg = (s.create_dataframe(_T, num_partitions=3).group_by("k", "g")
           .agg(F.sum(F.col("v")).with_name("sv"),
                F.count_star().with_name("n")))
    flt = (s.create_dataframe(_T, num_partitions=2)
           .filter(F.col("v") > 500).group_by("k")
           .agg(F.max(F.col("v")).with_name("mx")))
    srt = (s.create_dataframe(_T, num_partitions=2)
           .filter(F.col("g") == 2).order_by(F.col("u").asc()))
    return [agg, flt, srt]


def _canon(df: pd.DataFrame) -> pd.DataFrame:
    return (df.sort_values(by=list(df.columns), kind="mergesort")
            .reset_index(drop=True))


def _run_mix(s):
    shapes = _shapes(s)
    return [(i, _canon(shapes[i].to_pandas())) for i in _ZIPF_MIX]


def test_mixed_tenant_serving_under_chaos(tmp_path, monkeypatch):
    mm = MemoryManager(64 * 1024 * 1024, 1 << 30,
                       str(tmp_path / "spill"))
    sem = DeviceSemaphore(2, timeout_s=120.0, wedge_timeout_ms=300,
                          memory=mm)
    elogs = {t: str(tmp_path / f"elog_{t}") for t, _, _ in _TENANTS}

    # ---- fault-free baseline through the SAME shared runtime --------
    base = _mk_session(mm, sem, "baseline", 3, 0.0,
                       str(tmp_path / "elog_base"))
    want = {i: df for i, df in _run_mix(base)}
    base._ctx.close()

    from spark_rapids_tpu.sched import admission as adm_mod
    ctl = adm_mod.CONTROLLER
    assert ctl is not None, "admission.enabled did not install"

    # ---- killed semaphore holder: dies HOLDING a permit -------------
    killer = threading.Thread(target=sem.acquire, name="killed-holder")
    killer.start()
    killer.join()
    time.sleep(0.35)           # past the wedge horizon before load

    # ---- chaos-armed mixed-tenant load ------------------------------
    install_chaos(ChaosController("mem.oom=p0.08", seed=18))
    results, errors = {}, {}

    def tenant_run(tenant, priority, share):
        try:
            s = _mk_session(mm, sem, tenant, priority, share,
                            elogs[tenant])
            try:
                results[tenant] = _run_mix(s)
            finally:
                s._ctx.close()
        except BaseException as e:   # noqa: BLE001 - surfaced below
            errors[tenant] = e

    threads = [threading.Thread(target=tenant_run, args=spec,
                                name=f"tenant-{spec[0]}")
               for spec in _TENANTS]
    t_load0 = time.monotonic()
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=180)
        assert not th.is_alive(), "tenant thread wedged"
    load_wall_s = time.monotonic() - t_load0
    install_chaos(None)
    assert not errors, f"tenant queries failed under chaos: {errors}"

    # fault-free-identical results for every tenant, every repetition
    for tenant, got in results.items():
        assert len(got) == len(_ZIPF_MIX)
        for i, df in got:
            pd.testing.assert_frame_equal(df, want[i], check_exact=True)

    # the dead holder's permit was reclaimed, not wedged around forever
    assert sem.wedges >= 1

    # ---- quota census: attribution clean, fully drained -------------
    st = mm.stats()
    assert set(st["tenant_used"]) <= {t for t, _, _ in _TENANTS}
    assert all(v == 0 for v in st["tenant_used"].values()), \
        f"tenant census residue: {st['tenant_used']}"
    for t, _, share in _TENANTS:
        assert st["tenant_quota"][t] == int(share * mm.budget)
    assert mm.audit_leaks() == []

    # ---- bounded admission latency (p99 over logged queuedMs) -------
    from spark_rapids_tpu.metrics.sketch import QuantileSketch
    from spark_rapids_tpu.tools.history import load_events
    queued_ms = []
    per_tenant_n = {}
    tail_sketches = {}
    for t, d in elogs.items():
        events, _ = load_events(d)
        ends = [e for e in events if e.get("event") == "queryEnd"
                and e.get("ok")]
        per_tenant_n[t] = len(ends)
        sk = tail_sketches.setdefault(t, QuantileSketch())
        for e in ends:
            assert e.get("tenant") == t
            assert e.get("admission") == "admitted"
            queued_ms.append(float(e.get("queuedMs")))
            sk.observe(float(e.get("durationMs")))
    assert len(queued_ms) == len(_TENANTS) * len(_ZIPF_MIX)
    p99 = float(np.percentile(queued_ms, 99))
    assert p99 < 60_000.0, f"unbounded admission latency: p99={p99}ms"

    # ---- shed burst: pressure refuses low-priority, then recovers ---
    key = ("serve-load-shed",)
    MemoryManager._instances[key] = mm
    shed_sess = _mk_session(mm, sem, "gamma", 1, 0.5, elogs["gamma"])
    try:
        mm.reserve_granted(1)         # pressure pool nonzero
        with pytest.raises(adm_mod.AdmissionRejected) as ei:
            _shapes(shed_sess)[0].collect_arrow()
        assert ei.value.reason == "shed"
        assert ei.value.retry_after_s > 0
        assert ei.value.tenant == "gamma"
        mm.release_granted(1)
        # recovery: pool drained past the clear horizon -> the SAME
        # query admits and returns the baseline bytes
        from spark_rapids_tpu.ops import server as srv_mod
        monkeypatch.setattr(srv_mod, "_GRANT_CLEAR_HORIZON_S", 0.0)
        retry = _canon(_shapes(shed_sess)[0].to_pandas())
        pd.testing.assert_frame_equal(retry, want[0], check_exact=True)
    finally:
        MemoryManager._instances.pop(key, None)
        shed_sess._ctx.close()
    shed_events, _ = load_events(elogs["gamma"])
    shed_recs = [e for e in shed_events if e.get("event") == "queryEnd"
                 and e.get("admission") == "shed"]
    assert shed_recs and "AdmissionRejected" in shed_recs[-1]["reason"]

    # ---- controller bookkeeping survived the battery ----------------
    cst = ctl.stats()
    assert cst["inFlight"] == 0 and cst["queued"] == []
    assert cst["admitted"] >= len(queued_ms)
    assert cst["rejected"].get("shed", 0) >= 1

    # ---- BENCH-style serving artifact (tools/regress-parseable) -----
    # per-tenant tail latencies come from the quantile SKETCH, not a
    # sorted array: the artifact records exactly what the live /slo
    # endpoint and merged /metrics quantiles would have reported
    details = {}
    for t, _, _ in _TENANTS:
        thr = per_tenant_n[t] / max(load_wall_s, 1e-6)
        p50, p95, p99t = tail_sketches[t].quantiles((0.5, 0.95, 0.99))
        details[t] = {"speedup": round(thr, 3), "placement": "device",
                      "queries": per_tenant_n[t],
                      "p50Ms": round(p50, 3), "p95Ms": round(p95, 3),
                      "p99Ms": round(p99t, 3)}
    thrs = [d["speedup"] for d in details.values()]
    artifact = {
        "geomean": round(float(np.exp(np.mean(np.log(thrs)))), 3),
        "placement_counts": {"device": len(details)},
        "details": details,
        "admission": {"p99QueuedMs": round(p99, 1),
                      "admitted": cst["admitted"],
                      "rejected": cst["rejected"]},
    }
    out = os.environ.get("SRTPU_SERVE_ARTIFACT",
                         str(tmp_path / "SERVE_r02.json"))
    with open(out, "w", encoding="utf-8") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
    from spark_rapids_tpu.tools.regress import load_bench
    parsed = load_bench(out)
    assert set(parsed["details"]) == {t for t, _, _ in _TENANTS}
    assert all(d["speedup"] > 0 for d in parsed["details"].values())
    assert all(d["p99Ms"] >= d["p50Ms"] > 0
               for d in parsed["details"].values())
    assert parsed["geomean"] > 0


def test_committed_serve_artifact_parses():
    """The committed SERVE_r01.json (one recorded run of the battery
    above) stays tools/regress-parseable — the serving analog of the
    BENCH_r* regression artifacts."""
    from spark_rapids_tpu.tools.regress import load_bench
    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "SERVE_r01.json")
    parsed = load_bench(path)
    assert set(parsed["details"]) == {t for t, _, _ in _TENANTS}
    assert parsed["geomean"] > 0
    assert parsed["placement_counts"] == {"device": 4}


def test_committed_serve_r02_artifact_parses():
    """The committed SERVE_r02.json (one recorded run of the battery
    above, ISSUE 20) carries sketch-derived per-tenant p50/p95/p99
    and stays tools/regress-parseable."""
    from spark_rapids_tpu.tools.regress import load_bench
    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "SERVE_r02.json")
    parsed = load_bench(path)
    assert set(parsed["details"]) == {t for t, _, _ in _TENANTS}
    assert parsed["geomean"] > 0
    for d in parsed["details"].values():
        assert d["p50Ms"] > 0
        assert d["p50Ms"] <= d["p95Ms"] <= d["p99Ms"]
