"""DataFrame set operations (r5; ref Spark's ReplaceOperators planning:
intersect/except as null-safe semi/anti joins, the ALL variants via
count join + row replication — GpuShuffledHashJoin + ReplicateRows)."""
import numpy as np
import pandas as pd
import pyarrow as pa

from harness import tpu_session
from spark_rapids_tpu.api import functions as F


def _frames(s):
    l = s.create_dataframe(pa.table({
        "k": pa.array([1, 1, 2, 2, 3, None, None, 4], pa.int64()),
        "v": pa.array(["a", "a", "b", "b", "c", None, None, "d"])}))
    r = s.create_dataframe(pa.table({
        "k": pa.array([1, 2, 2, None, 5], pa.int64()),
        "v": pa.array(["a", "b", "b", None, "e"])}))
    return l, r


def _rows(df):
    return sorted(((r["k"], r["v"]) for r in df.collect()),
                  key=lambda x: (x[0] is None, x))


def test_intersect_distinct_nullsafe():
    s = tpu_session()
    l, r = _frames(s)
    got = _rows(l.intersect(r))
    # distinct left rows present in right; (None, None) MATCHES
    assert got == [(1, "a"), (2, "b"), (None, None)], got


def test_subtract_distinct_nullsafe():
    s = tpu_session()
    l, r = _frames(s)
    got = _rows(l.subtract(r))
    assert got == [(3, "c"), (4, "d")], got


def test_intersect_all_multiset():
    s = tpu_session()
    l, r = _frames(s)
    got = _rows(l.intersect_all(r))
    # counts: (1,a): min(2,1)=1; (2,b): min(2,2)=2; (None,None): min(2,1)=1
    assert got == [(1, "a"), (2, "b"), (2, "b"), (None, None)], got


def test_except_all_multiset():
    s = tpu_session()
    l, r = _frames(s)
    got = _rows(l.except_all(r))
    # (1,a): 2-1=1; (3,c): 1; (None,None): 2-1=1; (4,d): 1
    assert got == [(1, "a"), (3, "c"), (4, "d"), (None, None)], got


def test_setops_nan_semantics():
    """NaN == NaN and -0.0 == 0.0 in set operations (Spark)."""
    s = tpu_session()
    l = s.create_dataframe(pa.table({
        "x": pa.array([1.0, float("nan"), float("nan"), -0.0, 2.0])}))
    r = s.create_dataframe(pa.table({
        "x": pa.array([float("nan"), 0.0, 3.0])}))
    got = [r_["x"] for r_ in l.intersect(r).collect()]
    def norm(x):
        return "nan" if x != x else x
    assert sorted(map(norm, got), key=str) == [0.0, "nan"], got
    sub = [r_["x"] for r_ in l.subtract(r).collect()]
    assert sorted(map(norm, sub), key=str) == [1.0, 2.0], sub


def test_setops_larger_differential():
    """Random multiset differential vs a pandas oracle."""
    rng = np.random.RandomState(8)
    n = 5000
    mk = lambda seed: pa.table({
        "a": pa.array(np.random.RandomState(seed).randint(0, 40, n)),
        "b": pa.array(np.random.RandomState(seed + 1)
                      .choice(["x", "y", "z"], n))})
    s = tpu_session()
    lt, rt = mk(1), mk(2)
    l, r = s.create_dataframe(lt), s.create_dataframe(rt)

    def multiset(t):
        from collections import Counter
        return Counter(zip(t["a"].to_pylist(), t["b"].to_pylist()))

    lm, rm = multiset(lt), multiset(rt)
    got_ia = l.intersect_all(r).collect()
    from collections import Counter
    got_ia_c = Counter((r_["a"], r_["b"]) for r_ in got_ia)
    exp_ia = Counter({k: min(c, rm[k]) for k, c in lm.items()
                      if k in rm and min(c, rm[k]) > 0})
    assert got_ia_c == exp_ia
    got_ea_c = Counter((r_["a"], r_["b"])
                       for r_ in l.except_all(r).collect())
    exp_ea = Counter({k: c - rm.get(k, 0) for k, c in lm.items()
                      if c - rm.get(k, 0) > 0})
    assert got_ea_c == exp_ea


def test_sql_intersect_except():
    s = tpu_session()
    l, r = _frames(s)
    s.create_temp_view("l", l)
    s.create_temp_view("r", r)
    got = _rows(s.sql("SELECT k, v FROM l INTERSECT SELECT k, v FROM r"))
    assert got == [(1, "a"), (2, "b"), (None, None)], got
    got = _rows(s.sql("SELECT k, v FROM l EXCEPT ALL SELECT k, v FROM r"))
    assert got == [(1, "a"), (3, "c"), (4, "d"), (None, None)], got
    got = _rows(s.sql("SELECT k, v FROM l MINUS SELECT k, v FROM r"))
    assert got == [(3, "c"), (4, "d")], got
    n = s.sql("SELECT k, v FROM l UNION SELECT k, v FROM r").count()
    assert n == 6   # distinct union


def test_sql_setop_precedence_and_aliases():
    """INTERSECT binds tighter than UNION (SQL standard); positional
    column pairing; explicit DISTINCT keyword accepted; date columns."""
    import datetime
    s = tpu_session()
    s.create_temp_view("t1", s.create_dataframe(pa.table({"x": [1]})))
    s.create_temp_view("t2", s.create_dataframe(pa.table({"x": [2]})))
    s.create_temp_view("t3", s.create_dataframe(pa.table({"x": [2]})))
    got = sorted(r["x"] for r in s.sql(
        "SELECT x FROM t1 UNION SELECT x FROM t2 "
        "INTERSECT SELECT x FROM t3").collect())
    assert got == [1, 2], got       # t1 UNION (t2 INTERSECT t3)
    # positional pairing with different output names
    s.create_temp_view("u", s.create_dataframe(pa.table({"y": [1, 9]})))
    got = sorted(r["x"] for r in s.sql(
        "SELECT x FROM t1 INTERSECT DISTINCT SELECT y FROM u").collect())
    assert got == [1], got
    # DATE columns through set ops
    d = s.create_dataframe(pa.table(
        {"d": pa.array([datetime.date(2024, 1, 1),
                        datetime.date(2024, 1, 2), None])}))
    e = s.create_dataframe(pa.table(
        {"d": pa.array([datetime.date(2024, 1, 1), None])}))
    got = sorted(str(r["d"]) for r in d.intersect(e).collect())
    assert got == ["2024-01-01", "None"], got
