"""Tail-latency SLO layer tests (ISSUE 20): quantile sketch accuracy
and merge determinism, the pure burn-rate fold, the live SloTracker
(exemplars, per-digest attribution, burn alerts, shed hint), the
admission and AQE-feedback couplings, the sentinel ``tail_regression``
kind, the ``tools/history --slo`` replay, and the live-HTTP acceptance
bar: a ``GET /slo`` exemplar for an injected slow query resolves to an
actual on-disk trace artifact."""
import json
import math
import os
import socket
import urllib.error
import urllib.request

import numpy as np
import pyarrow as pa
import pytest

from harness import tpu_session
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.metrics.sketch import (QuantileSketch,
                                             fold_sketches)
from spark_rapids_tpu.ops.slo import (SloTracker, budget_remaining,
                                      burn_rate, fold_slo_event,
                                      install_slo, new_slo_state,
                                      parse_tenant_overrides)

_RNG = np.random.RandomState(20)
_N = 2048
_T = pa.table({
    "k": pa.array(_RNG.randint(0, 13, _N)),
    "v": pa.array(_RNG.randint(0, 1000, _N).astype(np.int64)),
})


def _get(port, path, timeout=10):
    r = urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                               timeout=timeout)
    return r.status, r.read().decode("utf-8")


def _get_any(port, path, timeout=10):
    try:
        return _get(port, path, timeout)
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")


# ---------------------------------------------------------------------------
# quantile sketch
# ---------------------------------------------------------------------------

def test_sketch_relative_error_bound():
    """Every quantile estimate is within the configured relative
    accuracy of the exact order statistic (the DDSketch guarantee)."""
    rng = np.random.RandomState(7)
    vals = np.abs(rng.lognormal(3.0, 1.5, 5000)) + 1e-6
    sk = QuantileSketch(alpha=0.01)
    for v in vals:
        sk.observe(float(v))
    exact = np.sort(vals)
    for q in (0.5, 0.9, 0.95, 0.99):
        est = sk.quantile(q)
        truth = float(exact[min(len(exact) - 1,
                                int(math.ceil(q * len(exact))) - 1)])
        assert abs(est - truth) <= 0.02 * truth, (q, est, truth)


def test_sketch_merge_equals_single_pass():
    """Merging N shard sketches is EXACTLY the single-pass sketch —
    bucket counts are integers, so merge order cannot drift."""
    rng = np.random.RandomState(11)
    vals = [float(v) for v in np.abs(rng.gamma(2.0, 50.0, 3000)) + 1e-6]
    whole = QuantileSketch()
    shards = [QuantileSketch() for _ in range(3)]
    for i, v in enumerate(vals):
        whole.observe(v)
        shards[i % 3].observe(v)
    merged = QuantileSketch()
    for sh in shards:
        merged.merge(sh)

    def bins_of(sk):
        d = sk.to_json()
        return {k: d[k] for k in ("alpha", "bins", "zero", "count",
                                  "min", "max")}
    # bucket counts are integers: merge == single pass EXACTLY (the
    # float running sum is the one field fp associativity can drift)
    assert bins_of(merged) == bins_of(whole)
    assert merged.sum == pytest.approx(whole.sum)
    folded = fold_sketches([sh.to_json() for sh in shards])
    assert bins_of(folded) == bins_of(whole)
    # and therefore every quantile is bit-identical
    qs = (0.5, 0.9, 0.95, 0.99)
    assert merged.quantiles(qs) == whole.quantiles(qs)
    assert folded.quantiles(qs) == whole.quantiles(qs)


def test_sketch_json_roundtrip_and_zero():
    sk = QuantileSketch()
    sk.observe(0.0)                       # below MIN_VALUE: zero bucket
    sk.observe(2.5)
    doc = json.loads(json.dumps(sk.to_json()))
    back = QuantileSketch.from_json(doc)
    assert back.count == 2
    assert back.to_json() == sk.to_json()
    assert QuantileSketch().quantile(0.99) == 0.0


def test_sketch_bin_cap_collapses_lowest():
    sk = QuantileSketch(max_bins=32)
    for v in np.geomspace(1e-6, 1e6, 500):
        sk.observe(float(v))
    assert len(sk.bins) <= 32
    # the collapse folds LOW buckets: the tail (what an SLO layer
    # actually reads) survives the cap
    assert sk.quantile(1.0) <= 1e6 * 1.03
    assert sk.quantile(0.99) > 4e5


# ---------------------------------------------------------------------------
# pure burn-rate fold
# ---------------------------------------------------------------------------

def test_fold_prunes_to_long_window_and_counts():
    st = new_slo_state()
    for i in range(10):
        fold_slo_event(st, tenant="a", ts=float(i), bad=(i % 2 == 0),
                       long_window_s=4.0)
    t = st["a"]
    assert t["good"] == 5 and t["bad"] == 5        # cumulative
    assert all(ts >= 9.0 - 4.0 for ts, _ in t["events"])  # pruned


def test_burn_rate_math():
    st = new_slo_state()
    # 10 events in-window, 2 bad, objective 0.99 -> burn = 0.2/0.01
    for i in range(10):
        fold_slo_event(st, tenant="a", ts=100.0 + i, bad=i < 2,
                       long_window_s=600.0)
    burn = burn_rate(st["a"], now=110.0, window_s=60.0, objective=0.99)
    assert abs(burn - 20.0) < 1e-9
    assert burn_rate(st["a"], now=110.0, window_s=60.0,
                     objective=1.0) == 1e9
    assert burn_rate({"events": []}, now=0.0, window_s=60.0,
                     objective=0.99) == 0.0


def test_budget_remaining_math():
    st = new_slo_state()
    for i in range(100):
        fold_slo_event(st, tenant="a", ts=float(i), bad=i < 2,
                       long_window_s=1e9)
    # 2 bad of 100 with a 1% budget: budget fully spent (clamped 0)
    assert budget_remaining(st["a"], objective=0.99) == 0.0
    assert budget_remaining({"events": []}, objective=0.99) == 1.0


def test_parse_tenant_overrides():
    ov = parse_tenant_overrides("alpha=500:0.999, beta=2000, bad, x=")
    assert ov["alpha"] == (500.0, 0.999)
    assert ov["beta"] == (2000.0, None)
    assert set(ov) == {"alpha", "beta"}


# ---------------------------------------------------------------------------
# live tracker
# ---------------------------------------------------------------------------

def _tracker(**kw):
    base = dict(target_ms=100.0, objective=0.9, short_window_s=10.0,
                long_window_s=60.0, burn_threshold=2.0, exemplar_cap=4,
                shed_enabled=True, digest_cap=3)
    base.update(kw)
    return SloTracker(**base)


def test_tracker_exemplars_and_digest_attribution():
    tr = _tracker()
    for i in range(6):
        tr.observe(tenant="a", wall_ms=50.0, ok=True, query_id=i,
                   digest="fast", ts=100.0 + i)
    tr.observe(tenant="a", wall_ms=400.0, ok=True, query_id=99,
               digest="slow", trace_path="/tmp/t.json",
               flight_path="/tmp/fb", ts=107.0)
    exs = tr.exemplars()
    assert len(exs) == 1 and exs[0]["queryId"] == 99
    assert exs[0]["trace"] == "/tmp/t.json"
    assert exs[0]["flight"] == "/tmp/fb"
    assert tr.digest_breaches("slow") == 1
    assert tr.digest_breaches("fast") == 0
    rep = tr.report(now=108.0)
    assert rep["worstDigests"][0]["digest"] == "slow"
    assert rep["worstDigests"][0]["excessMs"] == 300.0
    a = rep["tenants"]["a"]
    assert a["good"] == 6 and a["bad"] == 1


def test_tracker_exemplar_ring_and_digest_caps():
    tr = _tracker()
    for i in range(10):
        tr.observe(tenant="a", wall_ms=200.0, ok=True, query_id=i,
                   digest=f"d{i}", ts=100.0 + i)
    assert len(tr.exemplars()) == 4                 # exemplar_cap
    rep = tr.report(now=111.0)
    digs = {d["digest"] for d in rep["worstDigests"]}
    assert "other" in digs                          # digest_cap overflow
    assert len(digs) <= 4                           # 3 + "other"


def test_tracker_burn_alert_shed_hint_and_expiry():
    tr = _tracker()
    # every event bad: both windows burn at 1/0.1 = 10x >= threshold
    for i in range(5):
        tr.observe(tenant="a", wall_ms=500.0, ok=True, query_id=i,
                   digest="d", ts=100.0 + i)
    assert tr.alerts_fired == 1                     # cooldown: once
    assert tr.shed_hint(now=105.0) == "slo_burn:a"
    # the hint self-expires one short window after the last bad fold
    assert tr.shed_hint(now=104.0 + 10.0 + 0.1) is None
    h = tr.healthz(now=105.0)
    assert h["status"] == "degraded" and h["burningTenants"] == ["a"]
    # after the windows drain, healthz recovers without new events
    assert tr.healthz(now=300.0)["status"] == "ok"


def test_tracker_shed_disabled_never_hints():
    tr = _tracker(shed_enabled=False)
    for i in range(5):
        tr.observe(tenant="a", wall_ms=500.0, ok=True, ts=100.0 + i)
    assert tr.shed_hint(now=105.0) is None


def test_tracker_tenant_overrides():
    tr = _tracker(tenant_overrides={"gold": (50.0, 0.999)})
    assert tr.target_for("gold") == (50.0, 0.999)
    assert tr.target_for("default") == (100.0, 0.9)
    tr.observe(tenant="gold", wall_ms=80.0, ok=True, ts=100.0)
    assert tr.report(now=100.0)["tenants"]["gold"]["bad"] == 1


def test_tracker_failed_query_is_bad_even_under_target():
    tr = _tracker()
    tr.observe(tenant="a", wall_ms=10.0, ok=False, ts=100.0)
    rep = tr.report(now=100.0)
    assert rep["tenants"]["a"]["bad"] == 1
    assert rep["exemplars"] == []      # not over target: no exemplar


def test_tracker_never_raises_on_garbage():
    tr = _tracker()
    tr.observe(tenant=None, wall_ms=float("nan"), ok=True,
               ts=100.0)                 # must not raise


def test_admission_shed_reason_couples_to_burn():
    from spark_rapids_tpu.sched.admission import shed_reason
    tr = _tracker()
    install_slo(tr)
    try:
        assert shed_reason() is None
        import time as _time
        now = _time.time()
        for i in range(5):
            tr.observe(tenant="a", wall_ms=500.0, ok=True, ts=now)
        r = shed_reason()
        assert r is not None and "slo_burn:a" in r
    finally:
        install_slo(None)


def test_aqe_feedback_shrinks_batches_on_repeat_breaches():
    from spark_rapids_tpu.aqe.feedback import plan_feedback
    from spark_rapids_tpu.config import TpuConf
    tr = _tracker()
    install_slo(tr)
    try:
        conf = TpuConf()
        assert plan_feedback("dg", None, conf) is None
        for i in range(2):
            tr.observe(tenant="a", wall_ms=300.0, ok=True, digest="dg",
                       ts=100.0 + i)
        fb = plan_feedback("dg", None, conf)
        assert fb is not None and fb.mode == "smaller_batches"
        assert "SLO target 2x" in fb.reason
        assert set(fb.settings) == {
            "spark.rapids.tpu.sql.batchSizeBytes",
            "spark.rapids.tpu.sql.batchSizeRows"}
    finally:
        install_slo(None)


def test_slo_burn_fires_flight_trigger(tmp_path):
    from spark_rapids_tpu.ops import flight as fl_mod
    rec = fl_mod.FlightRecorder(str(tmp_path), rate_limit_ms=0)
    fl_mod.install_flight(rec)
    tr = _tracker()
    install_slo(tr)
    try:
        for i in range(5):
            tr.observe(tenant="a", wall_ms=500.0, ok=True, query_id=i,
                       digest="d", ts=100.0 + i)
        bundles = rec.stats()["bundles"]
        assert bundles and "slo_burn" in os.path.basename(bundles[-1])
        with open(os.path.join(bundles[-1], "placement.json"),
                  encoding="utf-8") as f:
            placement = json.load(f)
        assert placement["trigger"] == "slo_burn"
        detail = json.loads(placement["detail"])
        assert detail["tenant"] == "a"
        assert detail["exemplars"]
    finally:
        install_slo(None)
        fl_mod.install_flight(None)


# ---------------------------------------------------------------------------
# conf gating
# ---------------------------------------------------------------------------

def test_slo_disabled_by_default_no_tracker():
    from spark_rapids_tpu.ops import slo as slo_mod
    s = tpu_session()
    (s.create_dataframe(_T, num_partitions=2).group_by("k")
     .agg(F.sum(F.col("v")).with_name("sv"))).collect_arrow()
    assert slo_mod.TRACKER is None


def test_slo_conf_install_and_overrides():
    from spark_rapids_tpu.ops import slo as slo_mod
    s = tpu_session({
        "spark.rapids.tpu.slo.enabled": True,
        "spark.rapids.tpu.slo.targetMs": 250.0,
        "spark.rapids.tpu.slo.objective": 0.95,
        "spark.rapids.tpu.slo.tenant.overrides": "gold=50:0.999",
        "spark.rapids.tpu.slo.burn.threshold": 3.0})
    s.exec_context()
    tr = slo_mod.TRACKER
    assert tr is not None
    assert tr.target_ms == 250.0 and tr.objective == 0.95
    assert tr.burn_threshold == 3.0
    assert tr.target_for("gold") == (50.0, 0.999)


# ---------------------------------------------------------------------------
# sentinel tail_regression
# ---------------------------------------------------------------------------

def test_sentinel_tail_regression_flags_injected_p99_shift():
    from spark_rapids_tpu.ops.sentinel import fold_record
    baselines = {}
    rng = np.random.RandomState(3)
    # stable baseline: walls around 100ms with mild spread
    for _ in range(24):
        regs = fold_record(
            baselines, {"digest": "dg", "ok": True, "compileS": 0.0,
                        "wallMs": float(100.0 + rng.uniform(-5, 5))},
            wall_factor=1e9, tail_factor=2.0)
        assert regs == []
    # injected per-digest p99 regression: >2x the baselined p99
    regs = fold_record(
        baselines, {"digest": "dg", "ok": True, "compileS": 0.0,
                    "wallMs": 260.0},
        wall_factor=1e9, tail_factor=2.0)
    assert [r["kind"] for r in regs] == ["tail_regression"]
    assert regs[0]["digest"] == "dg"
    assert regs[0]["wallMs"] == 260.0
    assert regs[0]["factor"] >= 2.0
    # the flagged wall still folded in: persistently slower walls
    # re-baseline instead of alarming forever
    assert QuantileSketch.from_json(
        baselines["dg"]["tail"]).count >= 25


def test_sentinel_tail_sketch_decays_deterministically():
    from spark_rapids_tpu.ops.sentinel import fold_record
    baselines = {}
    for i in range(4 * 8 + 1):
        fold_record(baselines,
                    {"digest": "dg", "ok": True, "compileS": 0.0,
                     "wallMs": 100.0},
                    wall_factor=1e9, window=8, tail_factor=1e9)
    sk = QuantileSketch.from_json(baselines["dg"]["tail"])
    assert sk.count < 4 * 8          # halved at the 4x-window horizon
    assert abs(sk.quantile(0.99) - 100.0) / 100.0 < 0.02


def test_sentinel_cold_run_never_feeds_or_flags_tail():
    from spark_rapids_tpu.ops.sentinel import fold_record
    baselines = {}
    for _ in range(8):
        fold_record(baselines,
                    {"digest": "dg", "ok": True, "compileS": 0.0,
                     "wallMs": 100.0}, wall_factor=1e9)
    regs = fold_record(
        baselines, {"digest": "dg", "ok": True, "compileS": 1.5,
                    "wallMs": 5000.0}, wall_factor=1e9, tail_factor=2.0)
    assert regs == []                # compiled run: cold, exempt
    assert QuantileSketch.from_json(
        baselines["dg"]["tail"]).count == 8


def test_regress_replay_renders_tail_regression(tmp_path):
    from spark_rapids_tpu.tools.regress import (format_replay,
                                                replay_events)
    log = tmp_path / "events.jsonl"
    recs = [{"event": "queryEnd", "queryId": i, "planDigest": "dg",
             "ok": True, "compileSeconds": 0.0, "durationMs": 100.0}
            for i in range(10)]
    recs.append({"event": "queryEnd", "queryId": 10,
                 "planDigest": "dg", "ok": True,
                 "compileSeconds": 0.0, "durationMs": 300.0})
    log.write_text("\n".join(json.dumps(r) for r in recs) + "\n",
                   encoding="utf-8")
    from spark_rapids_tpu.tools.history import load_events
    events, _ = load_events(str(log))
    report = replay_events(events, wall_factor=1e9, tail_factor=2.0)
    kinds = [r["kind"] for r in report["regressions"]]
    assert "tail_regression" in kinds
    txt = format_replay(report)
    assert "TAIL_REGRESSION" in txt and "p99" in txt


# ---------------------------------------------------------------------------
# tools/history --slo replay
# ---------------------------------------------------------------------------

def _slo_log(tmp_path):
    recs = []
    for i in range(20):
        recs.append({"event": "queryEnd", "queryId": i, "ts": 100.0 + i,
                     "tenant": "alpha", "ok": True,
                     "durationMs": 50.0 + i})
    for i in range(10):
        recs.append({"event": "queryEnd", "queryId": 100 + i,
                     "ts": 100.0 + i, "tenant": "beta",
                     "ok": i % 2 == 0, "durationMs": 400.0})
    d = tmp_path / "elog"
    d.mkdir()
    (d / "events.jsonl").write_text(
        "\n".join(json.dumps(r) for r in recs) + "\n", encoding="utf-8")
    return d


def test_history_slo_replay_report(tmp_path):
    from spark_rapids_tpu.tools.history import (format_slo, load_events,
                                                slo_replay)
    events, _ = load_events(str(_slo_log(tmp_path)))
    rep = slo_replay(events, target_ms=200.0, objective=0.9)
    a, b = rep["tenants"]["alpha"], rep["tenants"]["beta"]
    assert a["bad"] == 0 and a["good"] == 20
    assert b["bad"] == 10 and b["good"] == 0    # all over 200ms target
    assert b["burn"]["long"] == 10.0            # 1.0 bad frac / 0.1
    assert a["errorBudgetRemaining"] == 1.0
    assert b["errorBudgetRemaining"] == 0.0
    assert 50.0 <= a["p50Ms"] <= 62.0
    assert abs(b["p99Ms"] - 400.0) / 400.0 < 0.02
    # identical logs -> identical report (replay determinism)
    assert rep == slo_replay(events, target_ms=200.0, objective=0.9)
    txt = format_slo(rep, source="elog")
    assert "alpha" in txt and "beta" in txt and "p99" in txt


def test_history_slo_cli_json(tmp_path, capsys):
    from spark_rapids_tpu.tools.history import main
    assert main([str(_slo_log(tmp_path)), "--slo", "200",
                 "--slo-objective", "0.9", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert set(doc["tenants"]) == {"alpha", "beta"}
    assert doc["targetMs"] == 200.0


# ---------------------------------------------------------------------------
# the live-HTTP acceptance bar
# ---------------------------------------------------------------------------

def test_slo_endpoint_stub_when_tracker_off():
    from spark_rapids_tpu.ops import server as srv_mod
    srv = srv_mod.install_ops(srv_mod.OpsServer(0).start())
    _, body = _get(srv.port, "/slo")
    assert json.loads(body) == {"enabled": False}


def test_live_http_slo_exemplar_resolves_to_artifacts(tmp_path):
    """The acceptance bar: an injected slow query (target 0.01ms — any
    real wall is over it) surfaces on GET /slo as an exemplar whose
    trace path is an actual artifact on disk, /metrics carries the
    OpenMetrics exemplar on the tenant's quantile series, and /healthz
    grows the slo section."""
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    trace_out = str(tmp_path / "trace.json")
    s = tpu_session({
        "spark.rapids.tpu.ops.port": port,
        "spark.rapids.tpu.metrics.enabled": True,
        "spark.rapids.tpu.trace.enabled": True,
        "spark.rapids.tpu.trace.output": trace_out,
        "spark.rapids.tpu.slo.enabled": True,
        "spark.rapids.tpu.slo.targetMs": 0.01})
    (s.create_dataframe(_T, num_partitions=2).group_by("k")
     .agg(F.sum(F.col("v")).with_name("sv"))).collect_arrow()

    _, body = _get(port, "/slo")
    doc = json.loads(body)
    assert doc["enabled"] is True
    assert doc["tenants"]["default"]["bad"] >= 1
    exs = doc["exemplars"]
    assert exs, "over-target query recorded no exemplar"
    ex = exs[0]
    assert ex["tenant"] == "default" and ex["wallMs"] > 0.01
    assert ex["trace"] == trace_out and os.path.exists(ex["trace"])
    with open(ex["trace"], encoding="utf-8") as f:
        assert json.load(f).get("traceEvents")
    assert doc["worstDigests"][0]["digest"] == ex["planDigest"]

    _, mbody = _get(port, "/metrics")
    qlines = [ln for ln in mbody.splitlines()
              if ln.startswith("srtpu_query_latency_seconds")]
    assert any('quantile="0.99"' in ln and 'tenant="default"' in ln
               for ln in qlines)
    excount = [ln for ln in qlines if "_count" in ln and " # {" in ln]
    assert excount, "no OpenMetrics exemplar on the summary series"
    assert "trace_path=" in excount[0]

    _, hbody = _get_any(port, "/healthz")
    hdoc = json.loads(hbody)
    assert "slo" in hdoc
    assert hdoc["slo"]["enabled"] is True
    assert hdoc["slo"]["verdict"] in ("ok", "degraded")
    assert hdoc["slo"]["exemplars"] >= 1
