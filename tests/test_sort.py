"""Differential tests for sort (ref sort_test.py). Spark ordering semantics:
NaN greatest, nulls first/last per order, -0.0 == 0.0."""
import pytest

from harness import assert_tpu_and_cpu_equal
from data_gen import BoolGen, DoubleGen, IntGen, LongGen, gen_df
from spark_rapids_tpu.api import functions as F


@pytest.mark.parametrize("gen", [IntGen(lo=-100, hi=100), LongGen(),
                                 DoubleGen(with_special=False)],
                         ids=["int", "long", "double"])
@pytest.mark.parametrize("asc", [True, False], ids=["asc", "desc"])
def test_single_key_sort(gen, asc):
    def q(s):
        df = s.create_dataframe(gen_df({"a": gen, "b": IntGen()}))
        o = F.col("a").asc() if asc else F.col("a").desc()
        return df.order_by(o, F.col("b").asc())
    assert_tpu_and_cpu_equal(q, ignore_order=False)


def test_multi_key_mixed_direction():
    def q(s):
        df = s.create_dataframe(gen_df({"a": IntGen(lo=0, hi=10),
                                        "b": IntGen(lo=0, hi=10),
                                        "c": IntGen()}))
        return df.order_by(F.col("a").asc(), F.col("b").desc(),
                           F.col("c").asc())
    assert_tpu_and_cpu_equal(q, ignore_order=False)


@pytest.mark.parametrize("asc,nulls_first", [(True, True), (True, False),
                                             (False, True), (False, False)])
def test_null_ordering(asc, nulls_first):
    def q(s):
        df = s.create_dataframe(gen_df({"a": IntGen(lo=0, hi=20),
                                        "b": IntGen()}))
        o = (F.col("a").asc(nulls_first) if asc
             else F.col("a").desc(nulls_first))
        return df.order_by(o, F.col("b").asc())
    assert_tpu_and_cpu_equal(q, ignore_order=False)


def test_sort_stability_via_tiebreak():
    def q(s):
        df = s.create_dataframe(gen_df({"a": IntGen(lo=0, hi=3),
                                        "b": IntGen()}))
        return df.order_by(F.col("a").asc(), F.col("b").asc())
    assert_tpu_and_cpu_equal(q, ignore_order=False)


def test_sort_int_min_desc():
    import pandas as pd
    import numpy as np

    def q(s):
        df = s.create_dataframe(pd.DataFrame(
            {"a": np.array([np.iinfo(np.int64).min, -1, 0, 5,
                            np.iinfo(np.int64).max], dtype=np.int64)}))
        return df.order_by(F.col("a").desc())
    assert_tpu_and_cpu_equal(q, ignore_order=False)
