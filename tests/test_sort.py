"""Differential tests for sort (ref sort_test.py). Spark ordering semantics:
NaN greatest, nulls first/last per order, -0.0 == 0.0."""
import pytest

from harness import assert_tpu_and_cpu_equal
from data_gen import BoolGen, DoubleGen, IntGen, LongGen, gen_df
from spark_rapids_tpu.api import functions as F


@pytest.mark.parametrize("gen", [IntGen(lo=-100, hi=100), LongGen(),
                                 DoubleGen(with_special=False)],
                         ids=["int", "long", "double"])
@pytest.mark.parametrize("asc", [True, False], ids=["asc", "desc"])
def test_single_key_sort(gen, asc):
    def q(s):
        df = s.create_dataframe(gen_df({"a": gen, "b": IntGen()}))
        o = F.col("a").asc() if asc else F.col("a").desc()
        return df.order_by(o, F.col("b").asc())
    assert_tpu_and_cpu_equal(q, ignore_order=False)


def test_multi_key_mixed_direction():
    def q(s):
        df = s.create_dataframe(gen_df({"a": IntGen(lo=0, hi=10),
                                        "b": IntGen(lo=0, hi=10),
                                        "c": IntGen()}))
        return df.order_by(F.col("a").asc(), F.col("b").desc(),
                           F.col("c").asc())
    assert_tpu_and_cpu_equal(q, ignore_order=False)


@pytest.mark.parametrize("asc,nulls_first", [(True, True), (True, False),
                                             (False, True), (False, False)])
def test_null_ordering(asc, nulls_first):
    def q(s):
        df = s.create_dataframe(gen_df({"a": IntGen(lo=0, hi=20),
                                        "b": IntGen()}))
        o = (F.col("a").asc(nulls_first) if asc
             else F.col("a").desc(nulls_first))
        return df.order_by(o, F.col("b").asc())
    assert_tpu_and_cpu_equal(q, ignore_order=False)


def test_sort_stability_via_tiebreak():
    def q(s):
        df = s.create_dataframe(gen_df({"a": IntGen(lo=0, hi=3),
                                        "b": IntGen()}))
        return df.order_by(F.col("a").asc(), F.col("b").asc())
    assert_tpu_and_cpu_equal(q, ignore_order=False)


def test_sort_int_min_desc():
    import pandas as pd
    import numpy as np

    def q(s):
        df = s.create_dataframe(pd.DataFrame(
            {"a": np.array([np.iinfo(np.int64).min, -1, 0, 5,
                            np.iinfo(np.int64).max], dtype=np.int64)}))
        return df.order_by(F.col("a").desc())
    assert_tpu_and_cpu_equal(q, ignore_order=False)


# ---------------------------------------------------------------------------
# Out-of-core sample sort (ref GpuOutOfCoreSortIterator GpuSortExec.scala:281)
# ---------------------------------------------------------------------------

_OOC_CONF = {"spark.rapids.tpu.sql.batchSizeBytes": 2048}


def test_out_of_core_sort_differential():
    def q(s):
        df = s.create_dataframe(gen_df(
            {"a": IntGen(lo=0, hi=1000), "b": DoubleGen(),
             "c": IntGen()}, n=4096), num_partitions=6)
        return df.order_by(F.col("a").asc(), F.col("b").desc())
    assert_tpu_and_cpu_equal(q, ignore_order=False, conf=_OOC_CONF)


def test_out_of_core_sort_nulls_and_desc():
    def q(s):
        df = s.create_dataframe(gen_df(
            {"a": IntGen(lo=0, hi=50, nullable=True),
             "b": DoubleGen(nullable=True)}, n=2048), num_partitions=4)
        return df.order_by(F.col("a").desc(), F.col("b").asc())
    assert_tpu_and_cpu_equal(q, ignore_order=False, conf=_OOC_CONF)


def test_out_of_core_sort_emits_multiple_sorted_batches():
    import pyarrow as pa
    from harness import tpu_session
    from spark_rapids_tpu.exec.sort import TpuSortExec
    s = tpu_session(_OOC_CONF)
    df = s.create_dataframe(gen_df({"a": IntGen()}, n=8192),
                            num_partitions=4).order_by(F.col("a").asc())
    phys = df._physical()
    assert isinstance(phys, TpuSortExec)
    ctx = s.exec_context()
    batches = list(phys.execute(ctx))
    assert len(batches) > 1, "expected bucketed out-of-core output"
    vals = pa.concat_tables([b.to_arrow() for b in batches])["a"]
    arr = vals.to_pandas()
    assert arr.dropna().is_monotonic_increasing


def test_out_of_core_skewed_keys():
    # heavy duplication: many splitters collapse into few distinct keys
    def q(s):
        df = s.create_dataframe(gen_df(
            {"a": IntGen(lo=0, hi=2), "b": IntGen()}, n=4096),
            num_partitions=4)
        return df.order_by(F.col("a").asc(), F.col("b").asc())
    assert_tpu_and_cpu_equal(q, ignore_order=False, conf=_OOC_CONF)
