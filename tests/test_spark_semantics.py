"""Ground-truth Spark-semantics battery (r5).

Hand-computed expected values from Spark's documented behavior, checked
on BOTH engines. Exists because twin-symmetric differential tests
cannot catch bugs the engines share (the lead()-as-lag() class): the
oracle here is Spark itself, not the sibling engine. Ref:
integration_tests' hand-written expected values in arithmetic_ops_test
/ string_test / hash_aggregate_test."""
import datetime
import math

import pyarrow as pa
import pytest

from harness import tpu_session
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.exprs import window_fns as WF

CASES = []


def case(name, build, expected):
    CASES.append(pytest.param(build, expected, id=name))



# --- arithmetic / math
case("int_div_by_zero_null",
     lambda s: s.create_dataframe(pa.table({"a": [6, 7]})).select(
         (F.col("a") / F.lit(0)).alias("o")),
     [None, None])                       # Spark: x / 0 -> NULL (non-ANSI)
case("remainder_by_zero_null",
     lambda s: s.create_dataframe(pa.table({"a": [6]})).select(
         (F.col("a") % F.lit(0)).alias("o")), [None])
case("round_half_up",
     lambda s: s.create_dataframe(pa.table({"a": [2.5, 3.5, -2.5]})).select(
         F.round(F.col("a")).alias("o")),
     [3.0, 4.0, -3.0])                   # Spark ROUND is HALF_UP
case("neg_mod_sign",
     lambda s: s.create_dataframe(pa.table({"a": [-7]})).select(
         (F.col("a") % F.lit(3)).alias("o")), [-1])  # Java %, not python
# --- strings
case("substring_negative_pos",
     lambda s: s.create_dataframe(pa.table({"x": ["hello"]})).select(
         F.substring(F.col("x"), -3, 2).alias("o")), ["ll"])
case("substring_pos_zero",
     lambda s: s.create_dataframe(pa.table({"x": ["hello"]})).select(
         F.substring(F.col("x"), 0, 3).alias("o")), ["hel"])
case("initcap_words",
     lambda s: s.create_dataframe(pa.table({"x": ["hELLO wORLD x2"]})).select(
         F.initcap(F.col("x")).alias("o")), ["Hello World X2"])
case("lpad_truncates",
     lambda s: s.create_dataframe(pa.table({"x": ["abcdef"]})).select(
         F.lpad(F.col("x"), 3).alias("o")), ["abc"])
case("split_default_keeps_trailing_empties",
     lambda s: s.create_dataframe(pa.table({"x": ["a,b,,"]})).select(
         F.split(F.col("x"), ",").alias("o")), [["a", "b", "", ""]])
case("concat_null_propagates",
     lambda s: s.create_dataframe(pa.table({"x": ["a", None]})).select(
         F.concat(F.col("x"), F.lit("b")).alias("o")), ["ab", None])
case("translate_map",
     lambda s: s.create_dataframe(pa.table({"x": ["ababab"]})).select(
         F.translate(F.col("x"), "ab", "b").alias("o")), ["bbb"])
# --- conditional / null
case("greatest_ignores_null",
     lambda s: s.create_dataframe(pa.table({"a": pa.array([1], pa.int64()),
                                            "b": pa.array([None], pa.int64())})).select(
         F.greatest(F.col("a"), F.col("b")).alias("o")), [1])
case("nullif_equal",
     lambda s: s.create_dataframe(pa.table({"a": [3, 4]})).select(
         F.nullif(F.col("a"), F.lit(3)).alias("o")), [None, 4])
# --- datetime
case("date_add_negative",
     lambda s: s.create_dataframe(pa.table({"d": pa.array([datetime.date(2024, 1, 1)])})).select(
         F.date_add(F.col("d"), F.lit(-1)).alias("o")),
     [datetime.date(2023, 12, 31)])
case("datediff_order",
     lambda s: s.create_dataframe(pa.table({
         "a": pa.array([datetime.date(2024, 1, 3)]),
         "b": pa.array([datetime.date(2024, 1, 1)])})).select(
         F.datediff(F.col("a"), F.col("b")).alias("o")), [2])
# --- aggregates
case("avg_ignores_null_counts_nan",
     lambda s: s.create_dataframe(pa.table({"v": [1.0, None, float("nan")]})).agg(
         F.avg(F.col("v")).with_name("o")), ["nan"])  # NaN propagates
case("min_nan_is_greatest",
     lambda s: s.create_dataframe(pa.table({"v": [float("nan"), 2.0]})).agg(
         F.min(F.col("v")).with_name("o")), [2.0])
case("max_picks_nan",
     lambda s: s.create_dataframe(pa.table({"v": [float("nan"), 2.0]})).agg(
         F.max(F.col("v")).with_name("o")), ["nan"])
case("count_star_counts_nulls",
     lambda s: s.create_dataframe(pa.table({"v": [None, None, 1]})).agg(
         F.count_star().with_name("o")), [3])
case("sum_empty_is_null",
     lambda s: s.create_dataframe(pa.table({"v": pa.array([], pa.int64())})).agg(
         F.sum(F.col("v")).with_name("o")), [None])


import datetime


case("cast_invalid_string_to_int_null",
     lambda s: s.create_dataframe(pa.table({"x": ["12abc", "7"]})).select(
         F.col("x").cast("int").alias("o")), [None, 7])
case("cast_string_trims_whitespace",
     lambda s: s.create_dataframe(pa.table({"x": [" 42 "]})).select(
         F.col("x").cast("int").alias("o")), [42])
case("cast_float_truncates_toward_zero",
     lambda s: s.create_dataframe(pa.table({"x": [3.99, -3.99]})).select(
         F.col("x").cast("int").alias("o")), [3, -3])
case("cast_bool_to_int",
     lambda s: s.create_dataframe(pa.table({"x": [True, False]})).select(
         F.col("x").cast("int").alias("o")), [1, 0])
case("concat_ws_skips_nulls",
     lambda s: s.create_dataframe(pa.table({"a": ["a"], "b": pa.array([None], pa.string()), "c": ["c"]})).select(
         F.concat_ws(",", F.col("a"), F.col("b"), F.col("c")).alias("o")),
     ["a,c"])
case("trim_is_space_only",
     lambda s: s.create_dataframe(pa.table({"x": ["  \ta b\t  "]})).select(
         F.trim(F.col("x")).alias("o")), ["\ta b\t"])
case("repeat_zero_empty",
     lambda s: s.create_dataframe(pa.table({"x": ["ab"]})).select(
         F.repeat(F.col("x"), 0).alias("o")), [""])
case("repeat_negative_empty",
     lambda s: s.create_dataframe(pa.table({"x": ["ab"]})).select(
         F.repeat(F.col("x"), -1).alias("o")), [""])
case("ascii_empty_zero",
     lambda s: s.create_dataframe(pa.table({"x": ["", "A"]})).select(
         F.ascii(F.col("x")).alias("o")), [0, 65])
case("pow_zero_zero",
     lambda s: s.create_dataframe(pa.table({"x": [0.0]})).select(
         F.pow(F.col("x"), F.lit(0.0)).alias("o")), [1.0])
case("substring_index_negative",
     lambda s: s.create_dataframe(pa.table({"x": ["a.b.c"]})).select(
         F.substring_index(F.col("x"), ".", -2).alias("o")), ["b.c"])
case("element_at_negative_one_last",
     lambda s: s.create_dataframe(pa.table({"x": [[1, 2, 3]]})).select(
         F.element_at(F.col("x"), -1).alias("o")), [3])
case("sort_array_nulls_first_asc",
     lambda s: s.create_dataframe(pa.table({"x": [[3, None, 1]]})).select(
         F.sort_array(F.col("x")).alias("o")), [[None, 1, 3]])
case("add_months_clamps_month_end",
     lambda s: s.create_dataframe(pa.table({"d": pa.array([datetime.date(2024, 1, 31)])})).select(
         F.add_months(F.col("d"), 1).alias("o")), [datetime.date(2024, 2, 29)])
case("least_all_null_is_null",
     lambda s: s.create_dataframe(pa.table({"a": pa.array([None], pa.int64()),
                                            "b": pa.array([None], pa.int64())})).select(
         F.least(F.col("a"), F.col("b")).alias("o")), [None])




case("in_list_with_null_is_null_when_absent",
     lambda s: s.create_dataframe(pa.table({"x": pa.array([3, 1], pa.int64())})).select(
         F.col("x").isin(1, None).alias("o")), [None, True])
case("rank_ties",
     lambda s: s.create_dataframe(pa.table({"v": [10, 10, 20]})).with_window_column(
         "o", WF.Rank(),
         order_by=[F.col("v").asc()]).select(F.col("o")).order_by(F.col("o").asc()),
     [1, 1, 3])
case("ntile_two_over_five",
     lambda s: s.create_dataframe(pa.table({"v": [1, 2, 3, 4, 5]})).with_window_column(
         "o", WF.NTile(2),
         order_by=[F.col("v").asc()]).select(F.col("o")).order_by(F.col("o").asc()),
     [1, 1, 1, 2, 2])
case("stddev_single_row_null",
     lambda s: s.create_dataframe(pa.table({"v": [5.0]})).agg(
         F.stddev(F.col("v")).with_name("o")), [None])
case("var_pop_single_row_zero",
     lambda s: s.create_dataframe(pa.table({"v": [5.0]})).agg(
         F.var_pop(F.col("v")).with_name("o")), [0.0])
case("like_escaped_percent",
     lambda s: s.create_dataframe(pa.table({"x": ["50%", "50x"]})).select(
         F.col("x").like("50\\%").alias("o")), [True, False])
case("cast_string_to_date",
     lambda s: s.create_dataframe(pa.table({"x": ["2024-02-29"]})).select(
         F.col("x").cast("date").alias("o")), [datetime.date(2024, 2, 29)])
case("cast_bool_strings",
     lambda s: s.create_dataframe(pa.table({"x": ["true", "false", "nope"]})).select(
         F.col("x").cast("boolean").alias("o")), [True, False, None])
case("array_contains_null_semantics",
     lambda s: s.create_dataframe(pa.table({"x": [[1, None], [1, 2]]})).select(
         F.array_contains(F.col("x"), 3).alias("o")), [None, False])
case("join_null_keys_never_match",
     lambda s: (lambda l, r: l.join(r, on="k").select(F.col("v")))(
         s.create_dataframe(pa.table({"k": pa.array([1, None], pa.int64()),
                                      "v": pa.array([10, 20], pa.int64())})),
         s.create_dataframe(pa.table({"k": pa.array([1, None], pa.int64()),
                                      "w": pa.array([5, 6], pa.int64())}))),
     [10])
case("left_join_unmatched_null",
     lambda s: (lambda l, r: l.join(r, on="k", how="left")
                .order_by(F.col("k").asc()).select(F.col("w")))(
         s.create_dataframe(pa.table({"k": pa.array([1, 2], pa.int64())})),
         s.create_dataframe(pa.table({"k": pa.array([1], pa.int64()),
                                      "w": pa.array([5], pa.int64())}))),
     [5, None])



ts_ = datetime.datetime(2024, 3, 15, 13, 45, 59,
                        tzinfo=datetime.timezone.utc)
case("hour_of_timestamp",
     lambda s: s.create_dataframe(pa.table({"t": pa.array([ts_])})).select(
         F.hour(F.col("t")).alias("o")), [13])
case("dayofweek_sunday_is_1",
     lambda s: s.create_dataframe(pa.table(
         {"d": pa.array([datetime.date(2024, 3, 17)])})).select(
         F.dayofweek(F.col("d")).alias("o")), [1])
case("cast_timestamp_to_date",
     lambda s: s.create_dataframe(pa.table({"t": pa.array([ts_])})).select(
         F.col("t").cast("date").alias("o")),
     [datetime.date(2024, 3, 15)])
case("floor_negative_half",
     lambda s: s.create_dataframe(pa.table({"x": [-2.5]})).select(
         F.floor(F.col("x")).alias("o")), [-3])
case("sequence_descending",
     lambda s: s.create_dataframe(pa.table({"a": [5]})).select(
         F.sequence(F.col("a"), F.lit(1)).alias("o")), [[5, 4, 3, 2, 1]])
case("string_compare_lexicographic",
     lambda s: s.create_dataframe(pa.table(
         {"x": ["apple", "Banana"]})).select(
         (F.col("x") > F.lit("Z")).alias("o")), [True, False])
# Spark NaN equality: double('NaN') IN (NaN) is TRUE (same _nan_eq
# semantics as EqualTo; ADVICE r5) — and NaN never matches non-NaN
case("nan_in_list",
     lambda s: s.create_dataframe(pa.table(
         {"a": [float("nan"), 1.0, 2.0]})).select(
         F.col("a").isin(float("nan"), 5.0).alias("o")),
     [True, False, False])
case("nan_in_list_with_match",
     lambda s: s.create_dataframe(pa.table(
         {"a": [float("nan"), 1.0, None]})).select(
         F.col("a").isin(float("nan"), 1.0).alias("o")),
     [True, True, None])


def _norm(x):
    if x is None:
        return None
    if isinstance(x, float) and math.isnan(x):
        return "nan"
    return x


@pytest.mark.parametrize("build,expected", CASES)
@pytest.mark.parametrize("conf", [
    pytest.param({"spark.rapids.tpu.distributed.enabled": False},
                 id="device"),
    pytest.param({"spark.rapids.tpu.sql.enabled": False}, id="host"),
])
def test_spark_semantics(build, expected, conf):
    s = tpu_session(conf)
    got = [_norm(list(r.values())[0])
           for r in build(s).collect()]
    assert got == [_norm(x) for x in expected]
