"""SQL front-end tests: text -> logical plan -> differential vs the
DataFrame formulation and vs the host oracle (the reference consumes SQL
through Spark's parser; this framework ships its own ANSI analytics
subset — spark_rapids_tpu/sql/)."""
import os
import sys

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from benchmarks import tpcds, tpch
from harness import tpu_session
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.sql.parser import SqlError


def _sess():
    s = tpu_session()
    s.create_dataframe(tpch.gen_lineitem(10_000)) \
        .create_or_replace_temp_view("lineitem")
    s.create_dataframe(tpcds.gen_store_sales(8_000)) \
        .create_or_replace_temp_view("store_sales")
    s.create_dataframe(tpcds.gen_date_dim()) \
        .create_or_replace_temp_view("date_dim")
    s.create_dataframe(tpcds.gen_item()) \
        .create_or_replace_temp_view("item")
    return s


def test_sql_tpch_q1_matches_dataframe():
    s = _sess()
    got = s.sql("""
        SELECT l_returnflag, l_linestatus,
               sum(l_quantity) AS sum_qty,
               sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
               avg(l_discount) AS avg_disc,
               count(*) AS count_order
        FROM lineitem
        WHERE l_shipdate <= date '1998-12-01' - interval '90' day
        GROUP BY l_returnflag, l_linestatus
        ORDER BY l_returnflag, l_linestatus""").to_pandas()
    assert len(got) == 6
    exp = tpch.q1(s.create_dataframe(tpch.gen_lineitem(10_000)), F) \
        .to_pandas()
    np.testing.assert_allclose(got["sum_disc_price"],
                               exp["sum_disc_price"], rtol=1e-12)


def test_sql_tpcds_q3_join():
    s = _sess()
    got = s.sql("""
        SELECT d_year, i_brand_id, i_brand,
               sum(ss_ext_sales_price) AS sum_agg
        FROM date_dim, store_sales, item
        WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
          AND i_manufact_id = 128 AND d_moy = 11
        GROUP BY d_year, i_brand_id, i_brand
        ORDER BY d_year, sum_agg DESC, i_brand_id""").to_pandas()
    exp = tpcds.q3(s.create_dataframe(tpcds.gen_store_sales(8_000)),
                   s.create_dataframe(tpcds.gen_date_dim()),
                   s.create_dataframe(tpcds.gen_item()), F).to_pandas()
    assert len(got) == len(exp)
    np.testing.assert_allclose(sorted(got["sum_agg"]),
                               sorted(exp["sum_agg"]), rtol=1e-12)


def test_sql_explicit_join_on_and_using():
    s = _sess()
    a = s.sql("""SELECT d_year, count(*) AS n
                 FROM store_sales JOIN date_dim
                      ON ss_sold_date_sk = d_date_sk
                 GROUP BY d_year ORDER BY d_year""").to_pandas()
    assert a["n"].sum() == 8000
    s.create_dataframe(pa.table({"k": [1, 2, 3], "x": [10, 20, 30]})) \
        .create_or_replace_temp_view("t1")
    s.create_dataframe(pa.table({"k": [2, 3, 4], "y": [5, 6, 7]})) \
        .create_or_replace_temp_view("t2")
    u = s.sql("SELECT k, x, y FROM t1 JOIN t2 USING (k) ORDER BY k") \
        .to_pandas()
    assert list(u["k"]) == [2, 3] and list(u.columns) == ["k", "x", "y"]
    lo = s.sql("SELECT k, x, y FROM t1 LEFT JOIN t2 USING (k) ORDER BY k") \
        .to_pandas()
    assert len(lo) == 3 and lo["y"].isna().sum() == 1


def test_sql_case_when_and_conditional_agg():
    s = _sess()
    got = s.sql("""
        SELECT count(CASE WHEN ss_quantity BETWEEN 1 AND 20
                          THEN 1 ELSE NULL END) AS b1,
               avg(CASE WHEN ss_quantity BETWEEN 1 AND 20
                        THEN ss_ext_sales_price ELSE NULL END) AS a1
        FROM store_sales""").to_pandas()
    raw = tpcds.gen_store_sales(8_000).to_pandas()
    m = (raw["ss_quantity"] >= 1) & (raw["ss_quantity"] <= 20)
    assert int(got["b1"][0]) == int(m.sum())
    np.testing.assert_allclose(got["a1"][0],
                               raw.loc[m, "ss_ext_sales_price"].mean(),
                               rtol=1e-9)


def test_sql_cte_having_union_limit():
    s = _sess()
    got = s.sql("""
        WITH big AS (
            SELECT l_orderkey, sum(l_quantity) AS q
            FROM lineitem GROUP BY l_orderkey HAVING sum(l_quantity) > 60
        )
        SELECT l_orderkey, q FROM big ORDER BY q DESC, l_orderkey
        LIMIT 10""").to_pandas()
    assert len(got) == 10 and (got["q"] > 60).all()
    assert list(got["q"]) == sorted(got["q"], reverse=True)

    u = s.sql("""
        SELECT 1 AS v FROM (SELECT l_orderkey FROM lineitem LIMIT 1) x
        UNION ALL
        SELECT 2 AS v FROM (SELECT l_orderkey FROM lineitem LIMIT 1) y
        ORDER BY v""").to_pandas()
    assert list(u["v"]) == [1, 2]


def test_sql_count_distinct_and_aliases():
    s = _sess()
    got = s.sql("""
        SELECT count(DISTINCT ss_item_sk) AS items,
               count(*) AS n, sum(ss_quantity) / count(*) AS avg_q
        FROM store_sales""").to_pandas()
    raw = tpcds.gen_store_sales(8_000).to_pandas()
    assert int(got["items"][0]) == raw["ss_item_sk"].nunique()
    assert int(got["n"][0]) == 8000
    np.testing.assert_allclose(got["avg_q"][0], raw["ss_quantity"].mean(),
                               rtol=1e-9)


def test_sql_scalar_fns_in_like_strings():
    s = _sess()
    t = pa.table({"name": ["Alice", "bob", "CAROL", None],
                  "v": [1.5, -2.5, 3.25, 4.0]})
    s.create_dataframe(t).create_or_replace_temp_view("people")
    got = s.sql("""
        SELECT upper(name) AS u, abs(v) AS av
        FROM people
        WHERE name IS NOT NULL AND lower(name) LIKE '%o%'
        ORDER BY u""").to_pandas()
    assert list(got["u"]) == ["BOB", "CAROL"]
    got2 = s.sql("SELECT v FROM people WHERE v IN (1.5, 4) ORDER BY v") \
        .to_pandas()
    assert list(got2["v"]) == [1.5, 4.0]
    n = s.sql("SELECT count(*) AS n FROM people "
              "WHERE name NOT LIKE '%o%' AND name IS NOT NULL").to_pandas()
    # LIKE is case-sensitive: 'bob' matches '%o%'; 'Alice' and 'CAROL'
    # (uppercase O) do not
    assert int(n["n"][0]) == 2


def test_sql_group_by_ordinal_and_alias():
    s = _sess()
    a = s.sql("""SELECT l_returnflag AS rf, count(*) AS n
                 FROM lineitem GROUP BY 1 ORDER BY 1""").to_pandas()
    b = s.sql("""SELECT l_returnflag AS rf, count(*) AS n
                 FROM lineitem GROUP BY rf ORDER BY rf""").to_pandas()
    pd.testing.assert_frame_equal(a, b)
    assert list(a["rf"]) == ["A", "N", "R"]


def test_sql_errors_are_actionable():
    s = _sess()
    with pytest.raises(SqlError, match="not found"):
        s.sql("SELECT * FROM nope")
    with pytest.raises(SqlError):
        s.sql("SELECT FROM lineitem")
    with pytest.raises(SqlError, match="unknown function"):
        s.sql("SELECT frobnicate(l_quantity) FROM lineitem")


def test_sql_order_by_agg_and_hidden_columns():
    s = _sess()
    got = s.sql("""SELECT l_returnflag FROM lineitem
                   GROUP BY l_returnflag ORDER BY count(*) DESC""") \
        .to_pandas()
    raw = tpch.gen_lineitem(10_000).to_pandas()
    exp = raw.groupby("l_returnflag").size().sort_values(ascending=False)
    assert list(got["l_returnflag"]) == list(exp.index)
    # aliased group key ordered by its source name
    got2 = s.sql("""SELECT l_returnflag AS rf, count(*) AS n FROM lineitem
                    GROUP BY l_returnflag ORDER BY l_returnflag""") \
        .to_pandas()
    assert list(got2["rf"]) == ["A", "N", "R"]


def test_sql_self_join_with_aliases():
    s = _sess()
    import pyarrow as pa
    s.create_dataframe(pa.table({"k": [1, 2, 3], "x": [10, 20, 30]})) \
        .create_or_replace_temp_view("t1")
    got = s.sql("""SELECT count(*) AS n FROM t1 a JOIN t1 b ON a.k = b.k""") \
        .to_pandas()
    assert int(got["n"][0]) == 3


def test_sql_using_right_and_full_outer_keys():
    s = _sess()
    import pyarrow as pa
    s.create_dataframe(pa.table({"k": [1, 2, 3], "x": [10, 20, 30]})) \
        .create_or_replace_temp_view("t1")
    s.create_dataframe(pa.table({"k": [2, 3, 4], "y": [5, 6, 7]})) \
        .create_or_replace_temp_view("t2")
    r = s.sql("SELECT k, y FROM t1 RIGHT JOIN t2 USING (k) ORDER BY k") \
        .to_pandas()
    assert list(r["k"]) == [2, 3, 4]
    f = s.sql("SELECT k FROM t1 FULL JOIN t2 USING (k) ORDER BY k") \
        .to_pandas()
    assert list(f["k"]) == [1, 2, 3, 4]


def test_sql_negative_in_semicolon_and_bad_ordinal():
    s = _sess()
    import pyarrow as pa
    s.create_dataframe(pa.table({"x": [-1, 2, 5]})) \
        .create_or_replace_temp_view("t")
    got = s.sql("SELECT x FROM t WHERE x IN (-1, 2) ORDER BY x;") \
        .to_pandas()
    assert list(got["x"]) == [-1, 2]
    with pytest.raises(SqlError, match="ordinal"):
        s.sql("SELECT x FROM t GROUP BY 0")
    with pytest.raises(SqlError, match="ordinal"):
        s.sql("SELECT x FROM t ORDER BY 5")


def test_sql_window_functions():
    s = _sess()
    t = pa.table({"g": ["a", "a", "a", "b", "b"],
                  "v": [3.0, 1.0, 2.0, 5.0, 4.0]})
    s.create_dataframe(t).create_or_replace_temp_view("w")
    got = s.sql("""
        SELECT g, v,
               row_number() OVER (PARTITION BY g ORDER BY v) AS rn,
               sum(v) OVER (PARTITION BY g) AS gs,
               lag(v, 1) OVER (PARTITION BY g ORDER BY v) AS pv
        FROM w ORDER BY g, v""").to_pandas()
    assert list(got["rn"]) == [1, 2, 3, 1, 2]
    assert list(got["gs"]) == [6.0, 6.0, 6.0, 9.0, 9.0]
    assert got["pv"].isna().sum() == 2    # first row of each partition
    assert list(got["pv"].dropna()) == [1.0, 2.0, 4.0]


def test_sql_window_running_sum_frame():
    s = _sess()
    t = pa.table({"v": [1.0, 2.0, 3.0, 4.0]})
    s.create_dataframe(t).create_or_replace_temp_view("w2")
    got = s.sql("""
        SELECT v, sum(v) OVER (ORDER BY v
            ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS rs
        FROM w2 ORDER BY v""").to_pandas()
    assert list(got["rs"]) == [1.0, 3.0, 6.0, 10.0]


def test_sql_window_over_aggregate_requires_subquery():
    s = _sess()
    with pytest.raises(SqlError, match="subquery"):
        s.sql("""SELECT l_returnflag, rank() OVER (ORDER BY sum(l_quantity))
                 FROM lineitem GROUP BY l_returnflag""")
    # the subquery formulation works
    got = s.sql("""
        SELECT rf, rank() OVER (ORDER BY sq DESC) AS r FROM
          (SELECT l_returnflag AS rf, sum(l_quantity) AS sq
           FROM lineitem GROUP BY l_returnflag) t
        ORDER BY r""").to_pandas()
    assert list(got["r"]) == [1, 2, 3]


def test_sql_window_extras():
    s = _sess()
    t = pa.table({"g": ["a", "a", "b", "b", None],
                  "v": pa.array([3.0, None, 2.0, 5.0, 4.0])})
    s.create_dataframe(t).create_or_replace_temp_view("wx")
    # ntile + negative lag default + trailing ';' + soft keyword column
    got = s.sql("""
        SELECT g, v, ntile(2) OVER (ORDER BY v NULLS FIRST) AS nt,
               lag(v, 1, -1) OVER (PARTITION BY g ORDER BY v) AS pv
        FROM wx ORDER BY g NULLS FIRST, v;""").to_pandas()
    assert set(got["nt"]) == {1, 2}
    assert (got["pv"].dropna() >= -1).all()
    # window in ORDER BY only
    r = s.sql("""SELECT v FROM wx
                 ORDER BY row_number() OVER (ORDER BY v DESC)""") \
        .to_pandas()
    assert list(r["v"].dropna()) == [5.0, 4.0, 3.0, 2.0]
    # DISTINCT inside a window is rejected loudly
    with pytest.raises(SqlError, match="DISTINCT"):
        s.sql("SELECT sum(DISTINCT v) OVER () FROM wx")
    # soft keywords usable as column names
    s.create_dataframe(pa.table({"rows": [1, 2], "current": [3, 4]})) \
        .create_or_replace_temp_view("soft")
    assert s.sql("SELECT rows, current FROM soft ORDER BY rows") \
        .count() == 2


def test_sql_rank_null_order_keys_tie():
    s = _sess()
    t = pa.table({"v": pa.array([None, None, 1.0, 2.0])})
    s.create_dataframe(t).create_or_replace_temp_view("nt")
    got = s.sql("""SELECT v, rank() OVER (ORDER BY v) AS r,
                          dense_rank() OVER (ORDER BY v) AS dr
                   FROM nt ORDER BY r, v""").to_pandas()
    assert list(got["r"]) == [1, 1, 3, 4]
    assert list(got["dr"]) == [1, 1, 2, 3]


def test_sql_tpc_query_texts_match_dataframe():
    """The canonical SQL texts (benchmarks/queries_sql.py) agree with the
    DataFrame formulations."""
    from benchmarks import queries_sql as Q
    s = tpu_session()
    Q.register_tpch(s, 10_000)
    Q.register_tpcds(s, 8_000)
    q1 = s.sql(Q.TPCH_Q1).to_pandas()
    e1 = tpch.q1(s.create_dataframe(tpch.gen_lineitem(10_000)), F) \
        .to_pandas()
    pd.testing.assert_frame_equal(q1, e1, check_exact=False, rtol=1e-12)
    q6 = s.sql(Q.TPCH_Q6).to_pandas()
    e6 = tpch.q6(s.create_dataframe(tpch.gen_lineitem(10_000)), F) \
        .to_pandas()
    np.testing.assert_allclose(q6["revenue"], e6["revenue"], rtol=1e-12)
    q3 = s.sql(Q.TPCDS_Q3).to_pandas()
    e3 = tpcds.q3(s.create_dataframe(tpcds.gen_store_sales(8_000)),
                  s.create_dataframe(tpcds.gen_date_dim()),
                  s.create_dataframe(tpcds.gen_item()), F).to_pandas()
    np.testing.assert_allclose(sorted(q3["sum_agg"]),
                               sorted(e3["sum_agg"]), rtol=1e-12)


def test_io_path_replacement(tmp_path):
    import pyarrow.parquet as pq
    real = tmp_path / "data"
    real.mkdir()
    pq.write_table(pa.table({"a": [1, 2, 3]}), str(real / "t.parquet"))
    s = tpu_session({"spark.rapids.tpu.io.pathReplacementRules":
                     f"s3://fake-bucket->{real}"})
    df = s.read_parquet("s3://fake-bucket/t.parquet")
    assert df.count() == 3


def test_shuffle_codec_conf():
    from harness import tpu_session
    import numpy as np
    t = pa.table({"k": pa.array(np.arange(5000) % 7),
                  "v": pa.array(np.ones(5000))})
    for codec in ("lz4", "zstd", "none"):
        s = tpu_session({"spark.rapids.tpu.shuffle.compression.codec": codec})
        out = s.create_dataframe(t).repartition(4, F.col("k")).count()
        assert out == 5000


def test_path_rules_and_codec_validation():
    import pytest
    from spark_rapids_tpu.io.file_scan import apply_path_rules
    from spark_rapids_tpu.config import TpuConf
    conf = TpuConf({"spark.rapids.tpu.io.pathReplacementRules": "s3://b"})
    with pytest.raises(ValueError, match="malformed"):
        apply_path_rules(conf, ["s3://b/x"])
    s = tpu_session({"spark.rapids.tpu.shuffle.compression.codec": "snappy"})
    t = pa.table({"k": [1, 2, 3]})
    with pytest.raises(ValueError, match="unsupported shuffle codec"):
        s.create_dataframe(t).repartition(2, F.col("k")).count()


def test_qualified_refs_with_colliding_join_columns():
    """t.k and r.k must stay distinct after a join (Spark keeps attributes
    by expression id; the lowerer renames collisions internally)."""
    t = pa.table({"k": [1, 2, 2], "v": [1.0, 2.0, 3.0]})
    r = pa.table({"k": [2, 3], "name": ["a", "b"]})
    for enabled in (True, False):
        s = tpu_session({"spark.rapids.tpu.sql.enabled": enabled})
        s.create_dataframe(t).create_or_replace_temp_view("t")
        s.create_dataframe(r).create_or_replace_temp_view("r")
        got = s.sql("""SELECT t.k, count(name) c FROM t LEFT JOIN r
                       ON t.k = r.k GROUP BY t.k ORDER BY t.k""").collect()
        assert got == [{"k": 1, "c": 0}, {"k": 2, "c": 2}]
        both = s.sql("SELECT t.k, r.k FROM t JOIN r ON t.k = r.k") \
            .collect_arrow()
        assert both.column_names == ["k", "k"]
        star = s.sql("SELECT r.* FROM t JOIN r ON t.k = r.k").collect()
        assert star == [{"k": 2, "name": "a"}, {"k": 2, "name": "a"}]
