"""String expression tests (ref string_test.py, regexp_test.py).

Strings are host-Arrow in both engines, so these validate against explicit
Python-computed expected values rather than differentially.
"""
import pandas as pd
import pytest

from harness import assert_tpu_and_cpu_equal, tpu_session
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.exprs import RegexUnsupported, transpile_java_regex


DATA = ["hello World", "", None, "Spark RAPIDS tpu", "aaa bbb  ccc",
        "héllo 中文", "x,y,z", "  padded  "]


def _df(s):
    return s.create_dataframe(pd.DataFrame({"s": DATA}))


def _run(col):
    s = tpu_session()
    out = _df(s).select(col.alias("r")).to_pandas()["r"].tolist()
    # normalize pandas NaN->None and nullable floats back to ints
    norm = []
    for v in out:
        if v is None or (isinstance(v, float) and pd.isna(v)):
            norm.append(None)
        elif isinstance(v, float) and v.is_integer():
            norm.append(int(v))
        else:
            norm.append(v)
    return norm


def _pyexpect(fn):
    return [None if v is None else fn(v) for v in DATA]


def test_length_upper_lower():
    assert _run(F.length(F.col("s"))) == _pyexpect(len)
    assert _run(F.upper(F.col("s"))) == _pyexpect(str.upper)
    assert _run(F.lower(F.col("s"))) == _pyexpect(str.lower)


def test_substring():
    assert _run(F.substring(F.col("s"), 1, 3)) == _pyexpect(lambda v: v[:3])
    assert _run(F.substring(F.col("s"), 2, 2)) == _pyexpect(lambda v: v[1:3])
    assert _run(F.substring(F.col("s"), -3)) == _pyexpect(lambda v: v[-3:])


def test_concat_null_propagates():
    out = _run(F.concat(F.col("s"), F.lit("!")))
    assert out == [None if v is None else v + "!" for v in DATA]


def test_predicates():
    assert _run(F.contains(F.col("s"), "o")) == _pyexpect(lambda v: "o" in v)
    assert _run(F.startswith(F.col("s"), "h")) == \
        _pyexpect(lambda v: v.startswith("h"))
    assert _run(F.endswith(F.col("s"), "c")) == \
        _pyexpect(lambda v: v.endswith("c"))


def test_like():
    out = _run(F.like(F.col("s"), "%o%d"))
    import re
    assert out == _pyexpect(lambda v: re.fullmatch(".*o.*d", v) is not None)


def test_trim_pad_reverse_repeat():
    assert _run(F.trim(F.col("s"))) == _pyexpect(str.strip)
    assert _run(F.ltrim(F.col("s"))) == _pyexpect(str.lstrip)
    assert _run(F.rpad(F.col("s"), 4, "*")) == \
        _pyexpect(lambda v: v.ljust(4, "*")[:4])
    assert _run(F.reverse(F.col("s"))) == _pyexpect(lambda v: v[::-1])
    assert _run(F.repeat(F.col("s"), 2)) == _pyexpect(lambda v: v * 2)


def test_regexp_replace_extract():
    assert _run(F.regexp_replace(F.col("s"), "[aeiou]", "#")) == \
        _pyexpect(lambda v: __import__("re").sub("[aeiou]", "#", v))
    out = _run(F.regexp_extract(F.col("s"), "(\\w+)", 1))
    import re
    rx = re.compile("([a-zA-Z0-9_]+)")
    assert out == _pyexpect(
        lambda v: (rx.search(v).group(1) if rx.search(v) else ""))


def test_substring_index_and_locate():
    assert _run(F.substring_index(F.col("s"), " ", 1)) == \
        _pyexpect(lambda v: v.split(" ")[0] if " " in v else v)
    assert _run(F.locate("o", F.col("s"))) == \
        _pyexpect(lambda v: v.find("o") + 1)


def test_filter_on_string_predicate_mixed_plan():
    """Plain-column string predicates now stay on the device filter
    (dictionary evaluation); predicates over COMPUTED strings still fall
    back to the CPU filter (per-exec fallback like the reference)."""
    s = tpu_session()
    df = s.create_dataframe(pd.DataFrame(
        {"s": ["aa", "ab", "ba", None], "v": [1, 2, 3, 4]}))
    out = (df.filter(F.startswith(F.col("s"), "a"))
           .select((F.col("v") * 10).alias("v10")))
    tree = out._physical().tree_string()
    assert "CpuFilter" not in tree and "* Project" in tree
    assert sorted(out.to_pandas()["v10"]) == [10, 20]

    out2 = (df.filter(F.startswith(F.upper(F.col("s")), "A"))
            .select((F.col("v") * 10).alias("v10")))
    tree2 = out2._physical().tree_string()
    assert "CpuFilter" in tree2, tree2
    assert sorted(out2.to_pandas()["v10"]) == [10, 20]


class TestRegexTranspiler:
    def test_ascii_classes(self):
        assert transpile_java_regex("\\d+") == "[0-9]+"
        assert transpile_java_regex("\\w") == "[a-zA-Z0-9_]"
        assert transpile_java_regex("[\\d]") == "[0-9]"

    def test_passthrough(self):
        assert transpile_java_regex("a(b|c)*d") == "a(b|c)*d"
        # `$` is NOT passthrough: Java's matches before a final
        # line terminator (r3 fix)
        import re as _re
        p = transpile_java_regex("^x{2,3}$")
        assert _re.search(p, "xx\n") and _re.search(p, "xxx")
        assert not _re.search(p, "xx\ny") and not _re.search(p, "x")

    def test_named_group(self):
        assert transpile_java_regex("(?<nm>a)") == "(?P<nm>a)"

    def test_java_z(self):
        assert transpile_java_regex("a\\z") == "a\\Z"

    @pytest.mark.parametrize("bad", ["\\p{L}", "[a[^b]]",
                                     "[a&&b]", "\\G", "(?"  "u)x"])
    def test_rejected(self, bad):
        with pytest.raises(RegexUnsupported):
            transpile_java_regex(bad)

    def test_unbalanced(self):
        with pytest.raises(RegexUnsupported):
            transpile_java_regex("(a")
        with pytest.raises(RegexUnsupported):
            transpile_java_regex("a)")


# ---------------------------------------------------------------------------
# dictionary-evaluated string predicates (VERDICT r1 #5): predicates run
# once over the sorted dictionary, broadcast through codes on device
# ---------------------------------------------------------------------------

def _str_table(n=2000, card=30, seed=3):
    import numpy as np
    import pyarrow as pa
    rng = np.random.RandomState(seed)
    words = [f"{p}_{i:03d}" for i, p in zip(
        range(card), ["apple", "apricot", "banana", "cherry", "date"] * card)]
    vals = [None if rng.rand() < 0.05 else words[rng.randint(card)]
            for _ in range(n)]
    return pa.table({"s": pa.array(vals),
                     "v": pa.array(rng.randint(0, 100, n).astype("int64"))})


def test_dict_filter_contains_differential():
    t = _str_table()

    def q(s):
        return (s.create_dataframe(t)
                .filter(F.col("s").contains("pri") & (F.col("v") > F.lit(10)))
                .agg(F.count_star().with_name("c"),
                     F.sum(F.col("v")).with_name("sv")))
    assert_tpu_and_cpu_equal(q)


def test_dict_filter_startswith_range_form():
    t = _str_table()

    def q(s):
        return (s.create_dataframe(t)
                .filter(F.col("s").startswith("ap"))
                .agg(F.count_star().with_name("c")))
    assert_tpu_and_cpu_equal(q)


def test_dict_filter_like_and_or():
    t = _str_table()

    def q(s):
        return (s.create_dataframe(t)
                .filter(F.col("s").like("%an%a%")
                        | (F.col("s").startswith("date")
                           & (F.col("v") < F.lit(50))))
                .agg(F.count_star().with_name("c"),
                     F.min(F.col("v")).with_name("mn")))
    assert_tpu_and_cpu_equal(q)


def test_dict_filter_stays_on_device_plan():
    t = _str_table()
    s = tpu_session()
    df = (s.create_dataframe(t)
          .filter(F.col("s").contains("err"))
          .agg(F.count_star().with_name("c")))
    tree = df._physical().tree_string()
    assert "CpuFilter" not in tree, tree
    assert "Filter" in tree


def test_dict_filter_string_output_columns_survive():
    """Filtered batches keep the string column intact (codes compacted on
    device, decode at the sink)."""
    t = _str_table(n=500)

    def q(s):
        return (s.create_dataframe(t)
                .filter(F.col("s").endswith("_001")))
    got = assert_tpu_and_cpu_equal(q)
    assert all(x.endswith("_001") for x in got["s"])


class TestRegexTranspilerR2:
    """Round-2 depth: \\Z, \\R, POSIX classes, nested unions, ASCII
    boundaries (ref RegexParser.scala coverage)."""

    def test_end_anchor_Z(self):
        import re
        p = transpile_java_regex("abc\\Z")
        assert re.search(p, "abc\n")      # before final terminator
        assert re.search(p, "abc")
        assert not re.search(p, "abc\n\n")

    def test_any_linebreak_R(self):
        import re
        p = transpile_java_regex("a\\Rb")
        assert re.search(p, "a\r\nb") and re.search(p, "a\nb")
        assert not re.search(p, "a b")

    def test_posix_classes(self):
        import re
        p = transpile_java_regex("\\p{Alpha}+\\p{Digit}")
        assert re.fullmatch(p, "abc7")
        assert not re.fullmatch(p, "ab7c")
        pn = transpile_java_regex("\\P{Digit}")
        assert re.fullmatch(pn, "x") and not re.fullmatch(pn, "5")
        pin = transpile_java_regex("[\\p{Upper}0-3]+")
        assert re.fullmatch(pin, "AB2")

    def test_unicode_category_rejected(self):
        with pytest.raises(RegexUnsupported):
            transpile_java_regex("\\p{L}+")

    def test_nested_class_union(self):
        import re
        p = transpile_java_regex("[a[bc]]+")
        assert re.fullmatch(p, "cab")
        assert not re.fullmatch(p, "d")
        with pytest.raises(RegexUnsupported):
            transpile_java_regex("[a[^b]]")
        with pytest.raises(RegexUnsupported):
            transpile_java_regex("[a&&[b]]")

    def test_ascii_word_boundary(self):
        import re
        p = transpile_java_regex("\\bword\\b")
        assert re.search(p, "a word here")
        # Java's ASCII \b: a unicode letter is NOT a word char
        assert re.search(p, "éwordé")

    def test_rlike_uses_extended_transpiler(self):
        s = tpu_session()
        df = s.create_dataframe(pd.DataFrame(
            {"s": ["abc1", "xyz", "ABC2", None]}))
        out = df.filter(F.rlike(F.col("s"), "\\p{Alpha}+\\p{Digit}")) \
            .to_pandas()
        assert sorted(out["s"]) == ["ABC2", "abc1"]


class TestRegexTargets:
    """The transpiler emits per-target syntax: RLike/RegExpReplace/
    StringSplit execute on pyarrow's RE2 engine (no lookaround, ASCII
    \\b already), RegExpExtract on Python re. These route boundary and
    anchor patterns END TO END through each engine (advisor r2 high)."""

    def test_rlike_word_boundary_end_to_end(self):
        s = tpu_session()
        df = s.create_dataframe(pd.DataFrame(
            {"s": ["a word here", "sword", "word", None]}))
        out = df.filter(F.rlike(F.col("s"), "\\bword\\b")).to_pandas()
        assert sorted(out["s"]) == ["a word here", "word"]

    def test_regexp_replace_word_boundary_end_to_end(self):
        assert _run(F.regexp_replace(F.col("s"), "\\bWorld\\b", "X")) == \
            _pyexpect(lambda v: v.replace("World", "X"))

    def test_rlike_end_anchor_Z_java_semantics(self):
        # Java \Z matches before one FINAL line terminator; in boolean
        # find mode the RE2 rewrite may consume it (same verdict)
        s = tpu_session()
        df = s.create_dataframe(pd.DataFrame(
            {"s": ["x", "x\n", "x\r\n", "x\n\n", "x\ny"]}))
        out = df.filter(F.rlike(F.col("s"), "x\\Z")).to_pandas()
        assert sorted(out["s"]) == ["x", "x\n", "x\r\n"]

    def test_rlike_dollar_java_semantics(self):
        # Java non-multiline $ == \Z (r3 review finding: RE2 $ is
        # end-of-text only, silently dropping the "x\n" row before)
        s = tpu_session()
        df = s.create_dataframe(pd.DataFrame(
            {"s": ["x", "x\n", "x\r", "x\n\n", "xy"]}))
        out = df.filter(F.rlike(F.col("s"), "x$")).to_pandas()
        assert sorted(out["s"]) == ["x", "x\n", "x\r"]

    def test_regexp_replace_dollar_keeps_terminator(self):
        # replace mode must NOT consume the final \n -> falls back to
        # the Python-re row loop where the lookahead rewrite applies
        s = tpu_session()
        df = s.create_dataframe(pd.DataFrame({"s": ["ax\n", "ax", "ay"]}))
        out = df.select(
            F.regexp_replace(F.col("s"), "x$", "Z").alias("r")
        ).to_pandas()["r"].tolist()
        assert out == ["aZ\n", "aZ", "ay"]

    def test_dot_excludes_java_line_terminators(self):
        # Java `.` excludes \r \x85    , not just \n
        s = tpu_session()
        df = s.create_dataframe(pd.DataFrame(
            {"s": ["a\rb", "a\nb", "a\x85b", "axb"]}))
        out = df.filter(F.rlike(F.col("s"), "a.b")).to_pandas()
        assert sorted(out["s"]) == ["axb"]
        # (?s) global prefix restores match-anything dot
        out = df.filter(F.rlike(F.col("s"), "(?s)a.b")).to_pandas()
        assert len(out) == 4

    def test_multiline_flag_rejected(self):
        with pytest.raises(RegexUnsupported):
            transpile_java_regex("(?m)^x$")
        with pytest.raises(RegexUnsupported):
            transpile_java_regex("a(?m:x$)b", target="re2")

    def test_rlike_lookaround_falls_back_to_python_engine(self):
        # RE2 can't run lookarounds; RLike transparently row-loops
        s = tpu_session()
        df = s.create_dataframe(pd.DataFrame(
            {"s": ["price: 10", "price: 9", None]}))
        out = df.filter(F.rlike(F.col("s"), "price: (?=1)\\d+")) \
            .to_pandas()
        assert out["s"].tolist() == ["price: 10"]

    def test_rlike_java_z_anchor(self):
        s = tpu_session()
        df = s.create_dataframe(pd.DataFrame({"s": ["x", "x\n", "ax"]}))
        out = df.filter(F.rlike(F.col("s"), "x\\z")).to_pandas()
        assert sorted(out["s"]) == ["ax", "x"]

    def test_regexp_extract_keeps_python_target(self):
        # extract runs on Python re, where \Z/\b rewrites still apply
        out = _run(F.regexp_extract(F.col("s"), "(\\w+)\\Z", 1))
        import re
        exp = []
        for v in DATA:
            if v is None:
                exp.append(None)
            else:
                m = re.search(r"(?a:(\w+))(?=\n?\Z)", v)
                exp.append("" if m is None else m.group(1))
        assert out == exp

    def test_re2_rejections_are_plan_time(self):
        for pat in ["(?=x)y", "(?<=x)y", "(?>xy)", "(x)\\1"]:
            with pytest.raises(RegexUnsupported):
                transpile_java_regex(pat, target="re2")
        # ...but python target keeps lookarounds
        assert transpile_java_regex("(?=x)y") == "(?=x)y"

    def test_linebreak_R_both_targets(self):
        s = tpu_session()
        df = s.create_dataframe(pd.DataFrame(
            {"s": ["a\nb", "a\r\nb", "a b", "ab"]}))
        out = df.filter(F.rlike(F.col("s"), "a\\Rb")).to_pandas()
        assert len(out) == 3


def test_split_limit_semantics_both_engines():
    """Spark limit: >0 = at most limit elements, <=0 = unlimited.
    Python re.split inverts the special maxsplit values (r3 review
    finding) — pin both the RE2 path and the lookahead-forced
    Python-re fallback."""
    s = tpu_session()
    df = s.create_dataframe(pd.DataFrame({"s": ["a:1b:2c:3d"]}))

    def run(pat, lim):
        return _df_split(df, pat, lim)

    def _df_split(df, pat, lim):
        out = df.select(
            F.split(F.col("s"), pat, lim).alias("r")).to_pandas()
        return list(out["r"][0])

    for pat in [":", ":(?=\\d)"]:        # RE2 path / python fallback
        assert _df_split(df, pat, -1) == ["a", "1b", "2c", "3d"]
        assert _df_split(df, pat, 0) == ["a", "1b", "2c", "3d"]
        assert _df_split(df, pat, 1) == ["a:1b:2c:3d"]
        assert _df_split(df, pat, 2) == ["a", "1b:2c:3d"]
        assert _df_split(df, pat, 3) == ["a", "1b", "2c:3d"]


def test_split_limit_zero_drops_trailing_empties():
    """Java Pattern.split limit=0: unlimited splits THEN trailing empty
    strings removed; limit=-1 keeps them (r3 review finding)."""
    s = tpu_session()
    df = s.create_dataframe(pd.DataFrame({"s": ["a:b::", "::", "a"]}))

    def run(pat, lim):
        out = df.select(
            F.split(F.col("s"), pat, lim).alias("r")).to_pandas()
        return [list(x) for x in out["r"]]

    for pat in [":", ":(?=.?)"]:          # RE2 path / python fallback
        assert run(pat, 0) == [["a", "b"], [], ["a"]]
        assert run(pat, -1) == [["a", "b", "", ""], ["", "", ""], ["a"]]


class TestDictTransforms:
    """Value-wise string transforms over dictionary-coded columns
    evaluate ONCE per distinct entry and re-encode (VERDICT r2 #4):
    row data never takes the per-row host detour."""

    def _dict_df(self, n=5000):
        s = tpu_session()
        rng = __import__("numpy").random.RandomState(3)
        vals = rng.choice(["Alpha", "beta ", " Gamma", "DELTA"], n)
        return s.create_dataframe(pd.DataFrame({"s": vals, "i": range(n)}))

    def test_transform_chain_evaluates_over_dictionary(self):
        import spark_rapids_tpu.exprs.string_fns as SF
        calls = []
        orig = SF.Upper.eval_host
        def spy(self, batch):
            calls.append(batch.num_rows)
            return orig(self, batch)
        SF.Upper.eval_host = spy
        try:
            df = self._dict_df()
            out = df.select(
                F.upper(F.trim(F.col("s"))).alias("u")).to_pandas()
        finally:
            SF.Upper.eval_host = orig
        assert sorted(set(out["u"])) == ["ALPHA", "BETA", "DELTA", "GAMMA"]
        # evaluated over the 4-entry dictionary, not the 5000 rows
        assert calls and max(calls) <= 4, calls

    def test_dict_transform_matches_host_engine(self):
        n = 2000
        rng = __import__("numpy").random.RandomState(8)
        vals = [None if x == "N" else x
                for x in rng.choice(["aa:bb", "cc:dd", "N", "e:f"], n)]
        pdf = pd.DataFrame({"s": vals})
        s = tpu_session()
        from harness import cpu_session
        cols = [F.substring(F.col("s"), 1, 2).alias("sub"),
                F.regexp_replace(F.col("s"), ":", "-").alias("rr"),
                F.upper(F.col("s")).alias("up")]
        got = s.create_dataframe(pdf).select(*cols).to_pandas()
        want = cpu_session().create_dataframe(pdf).select(*cols).to_pandas()
        for c in ("sub", "rr", "up"):
            assert got[c].fillna("<N>").tolist() == \
                want[c].fillna("<N>").tolist(), c

    def test_transformed_dict_predicate_falls_back_to_mask(self):
        # after upper(), the dictionary is unsorted: a prefix predicate
        # (range form) must still be correct via the contiguity guard
        df = self._dict_df()
        out = (df.select(F.upper(F.col("s")).alias("u"), F.col("i"))
               .filter(F.startswith(F.col("u"), "B"))
               .to_pandas())
        assert set(out["u"]) == {"BETA "}

    def test_transformed_dict_sorts_and_merges_correctly(self):
        """upper() can merge ('Alpha','ALPHA ') and reorder entries: the
        transformed dictionary must be re-sorted + deduped with codes
        remapped, or device sorts/windows order by stale codes (r3
        review finding)."""
        pdf = pd.DataFrame(
            {"s": ["Banana", "apple", "APPLE", "cherry"] * 50})
        s = tpu_session()
        out = (s.create_dataframe(pdf)
               .select(F.upper(F.col("s")).alias("u"))
               .sort(F.col("u").asc())
               .to_pandas())
        assert out["u"].tolist() == (["APPLE"] * 100 + ["BANANA"] * 50
                                     + ["CHERRY"] * 50)
        # grouping merges the case-folded duplicates into ONE group
        g = (s.create_dataframe(pdf)
             .select(F.upper(F.col("s")).alias("u"))
             .group_by("u").agg(F.count_star().with_name("n"))
             .to_pandas().sort_values("u").reset_index(drop=True))
        assert g["u"].tolist() == ["APPLE", "BANANA", "CHERRY"]
        assert g["n"].tolist() == [100, 50, 50]


# ---------------------------------------------------------------------------
# Byte-rectangle device strings (r4: VERDICT #4 — high cardinality)
# ---------------------------------------------------------------------------

def _high_card_table(n=60000, card=30000, seed=7):
    import numpy as np
    import pyarrow as pa
    rng = np.random.RandomState(seed)
    pool = np.asarray([f"  Item-{i:06d}-{'x' * (i % 9)}  "
                       for i in range(card)], dtype=object)
    return pa.table({"s": pa.array(pool[rng.randint(0, card, n)]),
                     "v": pa.array(rng.uniform(0, 10, n))})


def test_rect_column_engages_at_high_cardinality():
    from spark_rapids_tpu.columnar import ColumnarBatch
    from spark_rapids_tpu.columnar.strrect import ByteRectColumn
    b = ColumnarBatch.from_arrow(_high_card_table(20000, 15000))
    assert isinstance(b.columns[0], ByteRectColumn), type(b.columns[0])
    assert b.columns[0].ascii_only
    # exact string roundtrip through the rectangle
    got = b.to_arrow().column("s")
    want = _high_card_table(20000, 15000).column("s")
    assert got.to_pylist() == want.to_pylist()


def test_rect_transform_chain_differential():
    """upper(trim(s)) / substring / length / predicates over a rectangle
    column match the host engine exactly (high cardinality: the dict
    path is out of play)."""
    t = _high_card_table()

    def q(s):
        return (s.create_dataframe(t)
                .select(F.upper(F.trim(F.col("s"))).alias("u"),
                        F.substring(F.col("s"), 3, 6).alias("pre"),
                        F.length(F.col("s")).alias("ln"),
                        F.col("v")))
    assert_tpu_and_cpu_equal(q)


def test_rect_predicates_differential():
    t = _high_card_table(30000, 20000)

    def q(s):
        df = s.create_dataframe(t)
        return df.filter(F.col("s").contains("0123")) \
                 .select(F.col("s"), F.col("v"))
    assert_tpu_and_cpu_equal(q)


def test_rect_transform_used_on_device():
    """The chain must actually run on the rectangle (not host fallback)."""
    from harness import tpu_session
    from spark_rapids_tpu.columnar.strrect import ByteRectColumn
    s = tpu_session()
    df = (s.create_dataframe(_high_card_table(20000, 15000))
          .select(F.upper(F.trim(F.col("s"))).alias("u"), F.col("v")))
    phys = df._physical()
    batches = list(phys.execute(s.exec_context()))
    assert any(isinstance(b.columns[0], ByteRectColumn) for b in batches), \
        [type(b.columns[0]) for b in batches]


def test_rect_non_ascii_falls_back_to_host():
    import numpy as np
    import pyarrow as pa
    rng = np.random.RandomState(3)
    pool = np.asarray([f"wört-{i:05d}" for i in range(8000)], dtype=object)
    t = pa.table({"s": pa.array(pool[rng.randint(0, 8000, 16000)])})

    def q(s):
        return s.create_dataframe(t).select(
            F.upper(F.col("s")).alias("u"))
    assert_tpu_and_cpu_equal(q)


def test_rect_groupby_high_cardinality_differential():
    """The bench shape at high cardinality: group by TRANSFORMED rect
    strings — keys group on device via packed-word operands (r4
    VERDICT #4 'done' criterion path)."""
    t = _high_card_table(60000, 30000)

    def q(s):
        return (s.create_dataframe(t)
                .select(F.upper(F.trim(F.col("s"))).alias("u"),
                        F.substring(F.col("s"), 3, 6).alias("pre"),
                        F.col("v"))
                .group_by("u", "pre")
                .agg(F.sum(F.col("v")).with_name("sv"),
                     F.count_star().with_name("n")))
    assert_tpu_and_cpu_equal(q, approximate_float=True)


def test_rect_groupby_multibatch_differential():
    t = _high_card_table(60000, 25000)

    def q(s):
        return (s.create_dataframe(t, num_partitions=4)
                .select(F.upper(F.trim(F.col("s"))).alias("u"), F.col("v"))
                .group_by("u")
                .agg(F.sum(F.col("v")).with_name("sv"),
                     F.count_star().with_name("n"),
                     F.min(F.col("v")).with_name("mn")))
    assert_tpu_and_cpu_equal(q, approximate_float=True)


def test_rect_groupby_direct_column_with_nulls():
    import numpy as np
    import pyarrow as pa
    rng = np.random.RandomState(5)
    pool = np.asarray([f"key-{i:05d}" for i in range(9000)], dtype=object)
    vals = pool[rng.randint(0, 9000, 20000)].astype(object)
    vals[rng.rand(20000) < 0.05] = None
    t = pa.table({"s": pa.array(vals), "v": pa.array(rng.rand(20000))})

    def q(s):
        return (s.create_dataframe(t).group_by("s")
                .agg(F.sum(F.col("v")).with_name("sv"),
                     F.count_star().with_name("n")))
    assert_tpu_and_cpu_equal(q, approximate_float=True)


def test_rect_replace_pad_differential():
    """r5: StringReplace / Lpad / Rpad over rectangles (width growth,
    cyclic pad, truncation) match the host engine exactly."""
    t = _high_card_table(30000, 20000)

    def q(s):
        return (s.create_dataframe(t)
                .select(F.replace(F.col("s"), "Item", "Thing").alias("r1"),
                        F.replace(F.col("s"), "-", "").alias("r2"),
                        F.replace(F.col("s"), "x", "yz").alias("r3"),
                        F.lpad(F.trim(F.col("s")), 24, "*").alias("lp"),
                        F.rpad(F.trim(F.col("s")), 6, "ab").alias("rp"),
                        F.col("v")))
    assert_tpu_and_cpu_equal(q)


def test_rect_locate_instr_like_differential():
    t = _high_card_table(30000, 20000)

    def q(s):
        df = s.create_dataframe(t)
        return df.select(F.locate("-00", F.col("s")).alias("loc"),
                         F.instr(F.col("s"), "xx").alias("ins"),
                         F.col("s").like("%Item-0%").alias("lk1"),
                         F.col("s").like("  Item%").alias("lk2"),
                         F.col("s").like("%xx  ").alias("lk3"),
                         F.col("v"))
    assert_tpu_and_cpu_equal(q)


def test_rect_substring_index_reverse_differential():
    t = _high_card_table(30000, 20000)

    def q(s):
        df = s.create_dataframe(t)
        return df.select(
            F.substring_index(F.trim(F.col("s")), "-", 2).alias("p2"),
            F.substring_index(F.trim(F.col("s")), "-", -1).alias("m1"),
            F.reverse(F.trim(F.col("s"))).alias("rev"),
            F.col("v"))
    assert_tpu_and_cpu_equal(q)


def test_rect_new_ops_run_on_device():
    """The r5 ops must actually engage the rectangle kernel, not fall
    back to per-row host eval."""
    from harness import tpu_session
    s = tpu_session()
    df = (s.create_dataframe(_high_card_table(20000, 15000))
          .select(F.replace(F.col("s"), "Item", "I").alias("r"),
                  F.col("v")))
    exec_ = df._physical()
    node = exec_
    while node.children and not hasattr(node, "rect_chain"):
        node = node.children[0]
    assert getattr(node, "rect_chain", None), exec_.tree_string()


def test_rect_edgecases_empty_and_all_space():
    import pyarrow as pa
    vals = (["", "   ", "a", "-", "--", "a-b-c", "x" * 31, None,
             "ab-", "-ab", "a--b"] * 600)
    t = pa.table({"s": pa.array(vals + [f"u{i}" for i in range(9000)])})

    def q(s):
        df = s.create_dataframe(t)
        return df.select(
            F.replace(F.col("s"), "-", "=+").alias("r"),
            F.lpad(F.col("s"), 5).alias("lp"),
            F.rpad(F.col("s"), 3).alias("rp"),
            F.substring_index(F.col("s"), "-", 1).alias("s1"),
            F.substring_index(F.col("s"), "-", -2).alias("sm"),
            F.locate("-", F.col("s")).alias("lc"),
            F.reverse(F.col("s")).alias("rv"))
    assert_tpu_and_cpu_equal(q)


def test_pallas_rect_predicates_differential():
    """r5: the Pallas sliding-match kernels (interpret mode on CPU) must
    agree with both the XLA rect ops and the host engine."""
    from spark_rapids_tpu.exprs.pallas_rect import pallas_available
    if not pallas_available():
        import pytest
        pytest.skip("pallas not available")
    t = _high_card_table(30000, 20000)
    conf = {"spark.rapids.tpu.sql.pallas.enabled": True}

    def q(s):
        df = s.create_dataframe(t)
        return df.select(F.col("s").contains("0123").alias("c"),
                         F.startswith(F.col("s"), "  Item-0").alias("sw"),
                         F.endswith(F.col("s"), "x  ").alias("ew"),
                         F.locate("-00", F.col("s")).alias("lc"),
                         F.col("s").like("%Item-1%").alias("lk"),
                         F.col("v"))
    assert_tpu_and_cpu_equal(q, conf=conf)
    # and identical to the XLA rect path
    import pandas as pd
    a = q(tpu_session(conf)).to_pandas()
    b = q(tpu_session()).to_pandas()
    pd.testing.assert_frame_equal(a, b)


def test_rect_rlike_literal_routing_differential():
    """r5: RLIKE patterns that are plain (optionally anchored) literals
    run on the rectangle device path; real regexes stay host."""
    from spark_rapids_tpu.exprs.string_rect import (_rlike_literal_parts,
                                                    rect_supported_op)
    from spark_rapids_tpu.exprs import string_fns as SF
    assert _rlike_literal_parts("Item-00") == ("contains", "Item-00")
    assert _rlike_literal_parts("^Item") == ("startswith", "Item")
    assert _rlike_literal_parts("xx$") == ("endswith", "xx")
    assert _rlike_literal_parts("^ab$") == ("equals", "ab")
    assert _rlike_literal_parts("It.m") is None
    assert _rlike_literal_parts("a+") is None
    assert not rect_supported_op(SF.RLike(None, "a|b"))

    t = _high_card_table(25000, 18000)

    def q(s):
        df = s.create_dataframe(t)
        return df.select(F.rlike(F.col("s"), "Item-00").alias("r1"),
                         F.rlike(F.col("s"), "^  Item").alias("r2"),
                         F.rlike(F.col("s"), "xx  $").alias("r3"),
                         F.col("v"))
    assert_tpu_and_cpu_equal(q)
    assert_tpu_and_cpu_equal(
        q, conf={"spark.rapids.tpu.sql.pallas.enabled": True})
