"""String expression tests (ref string_test.py, regexp_test.py).

Strings are host-Arrow in both engines, so these validate against explicit
Python-computed expected values rather than differentially.
"""
import pandas as pd
import pytest

from harness import assert_tpu_and_cpu_equal, tpu_session
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.exprs import RegexUnsupported, transpile_java_regex


DATA = ["hello World", "", None, "Spark RAPIDS tpu", "aaa bbb  ccc",
        "héllo 中文", "x,y,z", "  padded  "]


def _df(s):
    return s.create_dataframe(pd.DataFrame({"s": DATA}))


def _run(col):
    s = tpu_session()
    out = _df(s).select(col.alias("r")).to_pandas()["r"].tolist()
    # normalize pandas NaN->None and nullable floats back to ints
    norm = []
    for v in out:
        if v is None or (isinstance(v, float) and pd.isna(v)):
            norm.append(None)
        elif isinstance(v, float) and v.is_integer():
            norm.append(int(v))
        else:
            norm.append(v)
    return norm


def _pyexpect(fn):
    return [None if v is None else fn(v) for v in DATA]


def test_length_upper_lower():
    assert _run(F.length(F.col("s"))) == _pyexpect(len)
    assert _run(F.upper(F.col("s"))) == _pyexpect(str.upper)
    assert _run(F.lower(F.col("s"))) == _pyexpect(str.lower)


def test_substring():
    assert _run(F.substring(F.col("s"), 1, 3)) == _pyexpect(lambda v: v[:3])
    assert _run(F.substring(F.col("s"), 2, 2)) == _pyexpect(lambda v: v[1:3])
    assert _run(F.substring(F.col("s"), -3)) == _pyexpect(lambda v: v[-3:])


def test_concat_null_propagates():
    out = _run(F.concat(F.col("s"), F.lit("!")))
    assert out == [None if v is None else v + "!" for v in DATA]


def test_predicates():
    assert _run(F.contains(F.col("s"), "o")) == _pyexpect(lambda v: "o" in v)
    assert _run(F.startswith(F.col("s"), "h")) == \
        _pyexpect(lambda v: v.startswith("h"))
    assert _run(F.endswith(F.col("s"), "c")) == \
        _pyexpect(lambda v: v.endswith("c"))


def test_like():
    out = _run(F.like(F.col("s"), "%o%d"))
    import re
    assert out == _pyexpect(lambda v: re.fullmatch(".*o.*d", v) is not None)


def test_trim_pad_reverse_repeat():
    assert _run(F.trim(F.col("s"))) == _pyexpect(str.strip)
    assert _run(F.ltrim(F.col("s"))) == _pyexpect(str.lstrip)
    assert _run(F.rpad(F.col("s"), 4, "*")) == \
        _pyexpect(lambda v: v.ljust(4, "*")[:4])
    assert _run(F.reverse(F.col("s"))) == _pyexpect(lambda v: v[::-1])
    assert _run(F.repeat(F.col("s"), 2)) == _pyexpect(lambda v: v * 2)


def test_regexp_replace_extract():
    assert _run(F.regexp_replace(F.col("s"), "[aeiou]", "#")) == \
        _pyexpect(lambda v: __import__("re").sub("[aeiou]", "#", v))
    out = _run(F.regexp_extract(F.col("s"), "(\\w+)", 1))
    import re
    rx = re.compile("([a-zA-Z0-9_]+)")
    assert out == _pyexpect(
        lambda v: (rx.search(v).group(1) if rx.search(v) else ""))


def test_substring_index_and_locate():
    assert _run(F.substring_index(F.col("s"), " ", 1)) == \
        _pyexpect(lambda v: v.split(" ")[0] if " " in v else v)
    assert _run(F.locate("o", F.col("s"))) == \
        _pyexpect(lambda v: v.find("o") + 1)


def test_filter_on_string_predicate_mixed_plan():
    """Plain-column string predicates now stay on the device filter
    (dictionary evaluation); predicates over COMPUTED strings still fall
    back to the CPU filter (per-exec fallback like the reference)."""
    s = tpu_session()
    df = s.create_dataframe(pd.DataFrame(
        {"s": ["aa", "ab", "ba", None], "v": [1, 2, 3, 4]}))
    out = (df.filter(F.startswith(F.col("s"), "a"))
           .select((F.col("v") * 10).alias("v10")))
    tree = out._physical().tree_string()
    assert "CpuFilter" not in tree and "* Project" in tree
    assert sorted(out.to_pandas()["v10"]) == [10, 20]

    out2 = (df.filter(F.startswith(F.upper(F.col("s")), "A"))
            .select((F.col("v") * 10).alias("v10")))
    tree2 = out2._physical().tree_string()
    assert "CpuFilter" in tree2, tree2
    assert sorted(out2.to_pandas()["v10"]) == [10, 20]


class TestRegexTranspiler:
    def test_ascii_classes(self):
        assert transpile_java_regex("\\d+") == "[0-9]+"
        assert transpile_java_regex("\\w") == "[a-zA-Z0-9_]"
        assert transpile_java_regex("[\\d]") == "[0-9]"

    def test_passthrough(self):
        assert transpile_java_regex("a(b|c)*d") == "a(b|c)*d"
        assert transpile_java_regex("^x{2,3}$") == "^x{2,3}$"

    def test_named_group(self):
        assert transpile_java_regex("(?<nm>a)") == "(?P<nm>a)"

    def test_java_z(self):
        assert transpile_java_regex("a\\z") == "a\\Z"

    @pytest.mark.parametrize("bad", ["\\p{L}", "[a[^b]]",
                                     "[a&&b]", "\\G", "(?"  "u)x"])
    def test_rejected(self, bad):
        with pytest.raises(RegexUnsupported):
            transpile_java_regex(bad)

    def test_unbalanced(self):
        with pytest.raises(RegexUnsupported):
            transpile_java_regex("(a")
        with pytest.raises(RegexUnsupported):
            transpile_java_regex("a)")


# ---------------------------------------------------------------------------
# dictionary-evaluated string predicates (VERDICT r1 #5): predicates run
# once over the sorted dictionary, broadcast through codes on device
# ---------------------------------------------------------------------------

def _str_table(n=2000, card=30, seed=3):
    import numpy as np
    import pyarrow as pa
    rng = np.random.RandomState(seed)
    words = [f"{p}_{i:03d}" for i, p in zip(
        range(card), ["apple", "apricot", "banana", "cherry", "date"] * card)]
    vals = [None if rng.rand() < 0.05 else words[rng.randint(card)]
            for _ in range(n)]
    return pa.table({"s": pa.array(vals),
                     "v": pa.array(rng.randint(0, 100, n).astype("int64"))})


def test_dict_filter_contains_differential():
    t = _str_table()

    def q(s):
        return (s.create_dataframe(t)
                .filter(F.col("s").contains("pri") & (F.col("v") > F.lit(10)))
                .agg(F.count_star().with_name("c"),
                     F.sum(F.col("v")).with_name("sv")))
    assert_tpu_and_cpu_equal(q)


def test_dict_filter_startswith_range_form():
    t = _str_table()

    def q(s):
        return (s.create_dataframe(t)
                .filter(F.col("s").startswith("ap"))
                .agg(F.count_star().with_name("c")))
    assert_tpu_and_cpu_equal(q)


def test_dict_filter_like_and_or():
    t = _str_table()

    def q(s):
        return (s.create_dataframe(t)
                .filter(F.col("s").like("%an%a%")
                        | (F.col("s").startswith("date")
                           & (F.col("v") < F.lit(50))))
                .agg(F.count_star().with_name("c"),
                     F.min(F.col("v")).with_name("mn")))
    assert_tpu_and_cpu_equal(q)


def test_dict_filter_stays_on_device_plan():
    t = _str_table()
    s = tpu_session()
    df = (s.create_dataframe(t)
          .filter(F.col("s").contains("err"))
          .agg(F.count_star().with_name("c")))
    tree = df._physical().tree_string()
    assert "CpuFilter" not in tree, tree
    assert "Filter" in tree


def test_dict_filter_string_output_columns_survive():
    """Filtered batches keep the string column intact (codes compacted on
    device, decode at the sink)."""
    t = _str_table(n=500)

    def q(s):
        return (s.create_dataframe(t)
                .filter(F.col("s").endswith("_001")))
    got = assert_tpu_and_cpu_equal(q)
    assert all(x.endswith("_001") for x in got["s"])


class TestRegexTranspilerR2:
    """Round-2 depth: \\Z, \\R, POSIX classes, nested unions, ASCII
    boundaries (ref RegexParser.scala coverage)."""

    def test_end_anchor_Z(self):
        import re
        p = transpile_java_regex("abc\\Z")
        assert re.search(p, "abc\n")      # before final terminator
        assert re.search(p, "abc")
        assert not re.search(p, "abc\n\n")

    def test_any_linebreak_R(self):
        import re
        p = transpile_java_regex("a\\Rb")
        assert re.search(p, "a\r\nb") and re.search(p, "a\nb")
        assert not re.search(p, "a b")

    def test_posix_classes(self):
        import re
        p = transpile_java_regex("\\p{Alpha}+\\p{Digit}")
        assert re.fullmatch(p, "abc7")
        assert not re.fullmatch(p, "ab7c")
        pn = transpile_java_regex("\\P{Digit}")
        assert re.fullmatch(pn, "x") and not re.fullmatch(pn, "5")
        pin = transpile_java_regex("[\\p{Upper}0-3]+")
        assert re.fullmatch(pin, "AB2")

    def test_unicode_category_rejected(self):
        with pytest.raises(RegexUnsupported):
            transpile_java_regex("\\p{L}+")

    def test_nested_class_union(self):
        import re
        p = transpile_java_regex("[a[bc]]+")
        assert re.fullmatch(p, "cab")
        assert not re.fullmatch(p, "d")
        with pytest.raises(RegexUnsupported):
            transpile_java_regex("[a[^b]]")
        with pytest.raises(RegexUnsupported):
            transpile_java_regex("[a&&[b]]")

    def test_ascii_word_boundary(self):
        import re
        p = transpile_java_regex("\\bword\\b")
        assert re.search(p, "a word here")
        # Java's ASCII \b: a unicode letter is NOT a word char
        assert re.search(p, "éwordé")

    def test_rlike_uses_extended_transpiler(self):
        s = tpu_session()
        df = s.create_dataframe(pd.DataFrame(
            {"s": ["abc1", "xyz", "ABC2", None]}))
        out = df.filter(F.rlike(F.col("s"), "\\p{Alpha}+\\p{Digit}")) \
            .to_pandas()
        assert sorted(out["s"]) == ["ABC2", "abc1"]
