"""Cost-based optimizer (ref CostBasedOptimizer.scala) and supported-ops
doc/CSV generation (ref TypeChecks.scala SupportedOpsDocs/SupportedOpsForTools)."""
import pytest

from harness import assert_tpu_and_cpu_equal, tpu_session
from data_gen import IntGen, gen_df
from spark_rapids_tpu.api import functions as F


def _q(s):
    df = s.create_dataframe(gen_df({"k": IntGen(lo=0, hi=9),
                                    "v": IntGen()}, n=256))
    return df.filter(F.col("v") > 0).group_by("k").agg(
        F.count_star().with_name("n"))


def test_cost_optimizer_reverts_when_device_expensive():
    s = tpu_session({
        "spark.rapids.tpu.sql.optimizer.enabled": True,
        "spark.rapids.tpu.sql.optimizer.tpu.exec.defaultRowCost": 100.0,
        "spark.rapids.tpu.sql.optimizer.transition.cost": 100.0,
    })
    tree = _q(s)._physical().tree_string()
    assert "Cpu" in tree, tree


def test_cost_optimizer_keeps_device_when_cheap():
    s = tpu_session({
        "spark.rapids.tpu.sql.optimizer.enabled": True,
    })
    tree = _q(s)._physical().tree_string()
    assert "CpuAggregate" not in tree and "CpuFilter" not in tree, tree


def test_cost_optimizer_results_still_correct():
    assert_tpu_and_cpu_equal(
        _q, conf={"spark.rapids.tpu.sql.optimizer.enabled": True,
                  "spark.rapids.tpu.sql.optimizer.tpu.exec.defaultRowCost": 100.0})


def test_supported_ops_doc_generation():
    from spark_rapids_tpu.tools import (generate_supported_ops_md,
                                        generate_operators_score_csv,
                                        generate_supported_exprs_csv)
    md = generate_supported_ops_md()
    assert "TpuHashJoinExec" in md and "Cast" in md
    assert md == generate_supported_ops_md(), "generation not deterministic"
    score = generate_operators_score_csv()
    assert "CPUOperator,Score" in score and "TpuSortExec" in score
    csv = generate_supported_exprs_csv()
    assert csv.count("\n") > 100, "expression inventory suspiciously small"


def test_expression_inventory_marks_host_only():
    from spark_rapids_tpu.tools import expression_inventory
    inv = {r["name"]: r for r in expression_inventory()}
    assert inv["Add"]["device"]
    # string functions run on host columns (honest fallback tagging)
    assert any(r["module"] == "string_fns" for r in inv.values())


def test_config_docs_cover_registry():
    from spark_rapids_tpu.config import all_entries, generate_docs
    docs = generate_docs()
    for e in all_entries():
        if not e.internal:
            assert e.key in docs, e.key


def test_api_validation_clean():
    """ref api_validation/ApiValidation.scala: the registries must conform
    to the exec/expression/aggregate interfaces with docs coverage."""
    from spark_rapids_tpu.tools.api_validation import validate_api
    assert validate_api() == []
