"""Cost-based optimizer (ref CostBasedOptimizer.scala) and supported-ops
doc/CSV generation (ref TypeChecks.scala SupportedOpsDocs/SupportedOpsForTools)."""
import pytest

from harness import assert_tpu_and_cpu_equal, tpu_session
from data_gen import IntGen, gen_df
from spark_rapids_tpu.api import functions as F


def _q(s):
    df = s.create_dataframe(gen_df({"k": IntGen(lo=0, hi=9),
                                    "v": IntGen()}, n=256))
    return df.filter(F.col("v") > 0).group_by("k").agg(
        F.count_star().with_name("n"))


def test_cost_optimizer_reverts_when_device_expensive():
    s = tpu_session({
        "spark.rapids.tpu.sql.optimizer.enabled": True,
        "spark.rapids.tpu.sql.optimizer.tpu.exec.defaultRowCost": 100.0,
        "spark.rapids.tpu.sql.optimizer.transition.cost": 100.0,
    })
    tree = _q(s)._physical().tree_string()
    assert "Cpu" in tree, tree


def test_cost_optimizer_keeps_device_when_cheap():
    # floor 0 = directly-attached TPU: per-row device advantage decides
    s = tpu_session({
        "spark.rapids.tpu.sql.optimizer.enabled": True,
        "spark.rapids.tpu.sql.optimizer.device.queryFloorSeconds": 0.0,
    })
    tree = _q(s)._physical().tree_string()
    assert "CpuAggregate" not in tree and "CpuFilter" not in tree, tree


def test_cost_optimizer_floor_reverts_small_queries():
    """Default (tunnel-calibrated) floor: a 256-row query loses to the
    per-query dispatch+fetch floor and runs whole-plan on the host engine
    (VERDICT r2 weak #1 — the engine must pick the winning engine)."""
    s = tpu_session({"spark.rapids.tpu.sql.optimizer.enabled": True})
    tree = _q(s)._physical().tree_string()
    assert "Cpu" in tree, tree


def test_cost_optimizer_keeps_device_at_scale():
    """A query whose host estimate exceeds device + floor stays device:
    aggregate over enough estimated rows (host ~1.2e-7 s/row vs floor)."""
    import numpy as np
    import pyarrow as pa
    s = tpu_session({"spark.rapids.tpu.sql.optimizer.enabled": True})
    n = 4_000_000
    t = pa.table({"k": pa.array(np.arange(n, dtype=np.int64) % 7),
                  "v": pa.array(np.ones(n))})
    df = (s.create_dataframe(t).filter(F.col("v") > 0)
          .group_by("k").agg(F.sum(F.col("v")).with_name("sv")))
    tree = df._physical().tree_string()
    assert "CpuAggregate" not in tree, tree


def test_cost_optimizer_uses_measured_rows():
    from spark_rapids_tpu.plan.cost import (_RUNTIME_ROWS, estimate_rows,
                                            plan_signature,
                                            record_runtime_rows)
    import pyarrow as pa
    s = tpu_session()
    t = pa.table({"v": pa.array(list(range(100)))})
    df = s.create_dataframe(t).filter(F.col("v") > 1_000_000)
    sig = plan_signature(df.plan)
    assert estimate_rows(df.plan) == 50.0        # crude halving guess
    df.collect_arrow()                           # actual: 0 rows
    assert sig in _RUNTIME_ROWS
    assert estimate_rows(df.plan) == 0.0         # measured feedback wins


def test_cost_optimizer_results_still_correct():
    assert_tpu_and_cpu_equal(
        _q, conf={"spark.rapids.tpu.sql.optimizer.enabled": True,
                  "spark.rapids.tpu.sql.optimizer.tpu.exec.defaultRowCost": 100.0})


def test_supported_ops_doc_generation():
    from spark_rapids_tpu.tools import (generate_supported_ops_md,
                                        generate_operators_score_csv,
                                        generate_supported_exprs_csv)
    md = generate_supported_ops_md()
    assert "TpuHashJoinExec" in md and "Cast" in md
    assert md == generate_supported_ops_md(), "generation not deterministic"
    score = generate_operators_score_csv()
    assert "CPUOperator,Score" in score and "TpuSortExec" in score
    csv = generate_supported_exprs_csv()
    assert csv.count("\n") > 100, "expression inventory suspiciously small"


def test_expression_inventory_marks_host_only():
    from spark_rapids_tpu.tools import expression_inventory
    inv = {r["name"]: r for r in expression_inventory()}
    assert inv["Add"]["device"]
    # string functions run on host columns (honest fallback tagging)
    assert any(r["module"] == "string_fns" for r in inv.values())


def test_config_docs_cover_registry():
    from spark_rapids_tpu.config import all_entries, generate_docs
    docs = generate_docs()
    for e in all_entries():
        if not e.internal:
            assert e.key in docs, e.key


def test_api_validation_clean():
    """ref api_validation/ApiValidation.scala: the registries must conform
    to the exec/expression/aggregate interfaces with docs coverage."""
    from spark_rapids_tpu.tools.api_validation import validate_api
    assert validate_api() == []


def test_per_expression_disable_conf_falls_back_to_host():
    """ref GpuOverrides.scala:3935 — every ExprRule carries an enable conf;
    disabling it forces host evaluation with an explain reason, results
    unchanged."""
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.api import TpuSession, functions as F
    from spark_rapids_tpu.plan.meta import (fallback_counts,
                                            reset_fallback_counts)

    t = pa.table({"a": pa.array(np.arange(50, dtype=np.int64))})

    def run(sess):
        return (sess.create_dataframe(t)
                .select((F.col("a") * 3).alias("b"))
                .collect_arrow().column("b").to_pylist())

    base = run(TpuSession())
    reset_fallback_counts()
    off = run(TpuSession(
        {"spark.rapids.tpu.sql.expression.Multiply": "false"}))
    assert base == off
    assert any("Multiply disabled by" in k for k in fallback_counts())


def test_per_exec_disable_conf_converts_to_cpu():
    """ref GpuOverrides.scala:4121 per-ExecRule confs: a disabled exec
    converts to the CPU twin; differential results identical."""
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.api import TpuSession, functions as F

    t = pa.table({"a": pa.array(np.arange(50, dtype=np.int64)),
                  "g": pa.array((np.arange(50) % 4).astype(np.int64))})

    def run(sess):
        out = (sess.create_dataframe(t)
               .filter(F.col("a") > 5)
               .group_by("g")
               .agg(F.sum(F.col("a")).with_name("s"))
               .collect_arrow().to_pydict())
        return sorted(zip(out["g"], out["s"]))

    assert run(TpuSession()) == run(TpuSession(
        {"spark.rapids.tpu.sql.exec.Filter": "false",
         "spark.rapids.tpu.sql.exec.Aggregate": "false"}))


def test_op_confs_registered_and_documented():
    from spark_rapids_tpu.plan.op_confs import ensure_op_confs
    ensure_op_confs()
    from spark_rapids_tpu.config import _REGISTRY, generate_docs
    n_expr = sum(1 for k in _REGISTRY
                 if k.startswith("spark.rapids.tpu.sql.expression."))
    n_exec = sum(1 for k in _REGISTRY
                 if k.startswith("spark.rapids.tpu.sql.exec."))
    # breadth parity target: reference registers 239 confs total
    # (RapidsConf.scala); per-op enables are the long tail there too
    assert n_expr > 120, n_expr
    assert n_exec > 15, n_exec
    assert len(_REGISTRY) > 200
    docs = generate_docs()
    assert "spark.rapids.tpu.sql.expression.Multiply" in docs


def test_scale_test_harness():
    """ref integration_tests scaletest: parameterized scale run with
    host-oracle verification and a machine-readable report."""
    from spark_rapids_tpu.tools.scale_test import run_scale_test
    rep = run_scale_test(20_000, ["q6", "q1"], iters=1)
    assert rep["rows"] == 20_000
    assert rep["queries"]["q6"]["verified"]
    assert rep["queries"]["q1"]["output_rows"] > 0
    assert rep["queries"]["q1"]["placement"] in ("host", "device")


# ---------------------------------------------------------------------------
# Adaptive-stats persistence (r4: VERDICT #7 — measured walls/rows survive
# process exit so a cold process plans a seen shape correctly first try)
# ---------------------------------------------------------------------------

def test_stats_store_roundtrip(tmp_path, monkeypatch):
    import importlib
    monkeypatch.setenv("SRTPU_STATS_PATH", str(tmp_path / "stats.json"))
    monkeypatch.setenv("SRTPU_STATS_PERSIST", "1")
    from spark_rapids_tpu.plan import cost, stats_store
    importlib.reload(stats_store)
    cost.record_engine_wall("Agg[x](Scan[#abc#])", "device", 1.25)
    cost.record_engine_wall("Agg[x](Scan[#abc#])", "device", 0.75)
    cost.record_engine_wall("Agg[x](Scan[#123456#])", "host", 0.5)  # local
    cost.record_runtime_rows("Filter[c](Scan[#abc#])", 42)
    stats_store.mark_dirty()
    stats_store.save()
    walls, rows = {}, {}
    stats_store._loaded = False
    stats_store.load_into(walls, rows)
    assert walls[("Agg[x](Scan[#abc#])", "device")] == (2, 0.75)
    # process-local "#<id>#" signatures must never persist
    assert ("Agg[x](Scan[#123456#])", "host") not in walls
    assert rows["Filter[c](Scan[#abc#])"] == 42
    # live entries win over persisted ones on merge
    walls2 = {("Agg[x](Scan[#abc#])", "device"): (5, 0.1)}
    stats_store._loaded = False
    stats_store.load_into(walls2, {})
    assert walls2[("Agg[x](Scan[#abc#])", "device")] == (5, 0.1)


def test_content_fingerprint_stable_and_distinct():
    import pyarrow as pa
    from spark_rapids_tpu.plan.cost import _pin_table
    t1 = pa.table({"a": [1, 2, 3], "b": ["x", "y", "z"]})
    t2 = pa.table({"a": [1, 2, 3], "b": ["x", "y", "z"]})
    t3 = pa.table({"a": [1, 2, 4], "b": ["x", "y", "z"]})
    assert _pin_table(t1) == _pin_table(t1)          # memo stable
    assert _pin_table(t1) == _pin_table(t2)          # content-addressed
    assert _pin_table(t1) != _pin_table(t3)          # data-sensitive


def test_measured_walls_flip_host_to_device():
    """r4: arbitration is BIDIRECTIONAL — when the measured device wall
    beats the measured host wall, the per-node model reverts must not
    fire (before this, a shape the model mispriced onto a slow host twin
    stayed there forever, walls ignored)."""
    import pyarrow as pa
    from harness import tpu_session
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.plan import cost

    t = pa.table({"k": list(range(100)) * 10, "v": [1.0] * 1000})
    conf = {"spark.rapids.tpu.sql.optimizer.enabled": True}

    def physical():
        s = tpu_session(conf)
        df = (s.create_dataframe(t).group_by("k")
              .agg(F.sum(F.col("v")).with_name("s")))
        return df, df._physical().tree_string()

    df, _tree = physical()
    sig = cost.plan_signature(df.plan)
    # poison: host measured fast twice -> host wholesale
    cost._ENGINE_WALLS.clear()
    cost.record_engine_wall(sig, "host", 0.001)
    cost.record_engine_wall(sig, "host", 0.001)
    cost.record_engine_wall(sig, "device", 5.0)
    cost.record_engine_wall(sig, "device", 5.0)
    _df, tree_host = physical()
    assert "!" in tree_host, tree_host          # host chosen
    # now the device wall measures faster -> device wholesale
    cost._ENGINE_WALLS.clear()
    cost.record_engine_wall(sig, "host", 5.0)
    cost.record_engine_wall(sig, "host", 5.0)
    cost.record_engine_wall(sig, "device", 0.001)
    cost.record_engine_wall(sig, "device", 0.001)
    _df, tree_dev = physical()
    assert "CpuAggregate" not in tree_dev, tree_dev
    cost._ENGINE_WALLS.clear()
