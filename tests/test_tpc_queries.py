"""TPC-H q1/q6 and TPC-DS q3/q9/q28 differential tests at tiny scale
(BASELINE.md config ladder steps 2-3; the reference's equivalents live in
the NDS suite + integration_tests/tpch/tpcds pytest marks). Also covers
the distinct-aggregate rewrite (ref Spark RewriteDistinctAggregates) and
string group keys on the device aggregation path."""
import sys
import os

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from benchmarks import tpch, tpcds
from harness import (assert_all_on_tpu, assert_tpu_and_cpu_equal,
                     tpu_session)
from spark_rapids_tpu.api import functions as F

N = 20_000


def test_tpch_q1_differential():
    def q(s):
        return tpch.q1(s.create_dataframe(tpch.gen_lineitem(N)), F)
    assert_tpu_and_cpu_equal(q, approximate_float=True)


def test_tpch_q1_agg_on_device():
    s = tpu_session()
    df = tpch.q1(s.create_dataframe(tpch.gen_lineitem(2048)), F)
    tree = df._physical().tree_string()
    assert "HashAggregate" in tree and "CpuAggregate" not in tree, tree


def test_tpch_q6_differential():
    def q(s):
        return tpch.q6(s.create_dataframe(tpch.gen_lineitem(N)), F)
    assert_tpu_and_cpu_equal(q, approximate_float=True)


def test_tpch_q6_all_on_tpu():
    def q(s):
        return tpch.q6(s.create_dataframe(tpch.gen_lineitem(2048)), F)
    assert_all_on_tpu(q)


def _dstables(s, n=N):
    return (s.create_dataframe(tpcds.gen_store_sales(n)),
            s.create_dataframe(tpcds.gen_date_dim()),
            s.create_dataframe(tpcds.gen_item()))


def test_tpcds_q3_differential():
    def q(s):
        ss, dd, it = _dstables(s)
        return tpcds.q3(ss, dd, it, F, manufact_id=128)
    assert_tpu_and_cpu_equal(q, approximate_float=True)


def test_tpcds_q9_differential():
    def q(s):
        ss, _, _ = _dstables(s)
        return tpcds.q9(ss, F)
    assert_tpu_and_cpu_equal(q, approximate_float=True)


def test_tpcds_q28_differential():
    def q(s):
        ss, _, _ = _dstables(s)
        return tpcds.q28(ss, F)
    assert_tpu_and_cpu_equal(q, approximate_float=True)


# ---------------------------------------------------------------------------
# distinct aggregates (the rewrite itself)
# ---------------------------------------------------------------------------

def _kv(s, n=4096, nulls=True):
    import pyarrow as pa
    rng = np.random.RandomState(3)
    v = rng.randint(0, 50, n).astype("float64")
    vmask = rng.random(n) < 0.1 if nulls else np.zeros(n, bool)
    return s.create_dataframe(pa.table({
        "k": pa.array(rng.randint(0, 7, n)),
        "v": pa.array(np.where(vmask, np.nan, v), mask=vmask),
        "w": pa.array(rng.randint(0, 1000, n).astype("int64")),
    }))


def test_count_distinct_grouped():
    def q(s):
        return _kv(s).group_by("k").agg(
            F.count_distinct(F.col("v")).with_name("cd"),
            F.count(F.col("v")).with_name("c"),
            F.sum(F.col("w")).with_name("sw"),
            F.avg(F.col("v")).with_name("av"),
            F.count_star().with_name("n"))
    assert_tpu_and_cpu_equal(q, approximate_float=True)


def test_distinct_agg_global():
    def q(s):
        return _kv(s).agg(
            F.count_distinct(F.col("v")).with_name("cd"),
            F.sum_distinct(F.col("v")).with_name("sd"),
            F.avg_distinct(F.col("v")).with_name("ad"),
            F.max(F.col("w")).with_name("mx"))
    assert_tpu_and_cpu_equal(q, approximate_float=True)


def test_distinct_agg_zero_rows():
    def q(s):
        df = _kv(s, n=64)
        return df.filter(F.col("w") < F.lit(-1)).agg(
            F.count_distinct(F.col("v")).with_name("cd"),
            F.count_star().with_name("n"))
    assert_tpu_and_cpu_equal(q)


def test_distinct_agg_runs_on_device():
    s = tpu_session()
    df = _kv(s).group_by("k").agg(
        F.count_distinct(F.col("v")).with_name("cd"))
    tree = df._physical().tree_string()
    assert "CpuAggregate" not in tree, tree


def test_multi_column_distinct_falls_back():
    """Two different distinct columns cannot expand -> host aggregate."""
    def q(s):
        return _kv(s).group_by("k").agg(
            F.count_distinct(F.col("v")).with_name("cdv"),
            F.count_distinct(F.col("w")).with_name("cdw"))
    t = assert_tpu_and_cpu_equal(q)
    assert len(t) == 7


# ---------------------------------------------------------------------------
# host-batch consumers (the aggregate single-fetch path emits host batches;
# every downstream device exec must re-materialize via ensure_device)
# ---------------------------------------------------------------------------

def test_agg_output_feeds_repartition_and_join():
    import pyarrow as pa
    s = tpu_session()
    t = pa.table({"k": pa.array(np.arange(1000) % 7),
                  "v": pa.array(np.ones(1000))})
    agg = s.create_dataframe(t).group_by("k").agg(
        F.sum(F.col("v")).with_name("sv"))
    assert agg.repartition(4).count() == 7
    other = s.create_dataframe(pa.table({"k2": pa.array([0, 1, 2])}))
    j = agg.join(other, on=[("k", "k2")], how="inner")
    assert j.count() == 3


def test_agg_output_feeds_window():
    import pyarrow as pa
    s = tpu_session()
    t = pa.table({"k": pa.array(np.arange(100) % 5),
                  "v": pa.array(np.arange(100, dtype="float64"))})
    agg = s.create_dataframe(t).group_by("k").agg(
        F.sum(F.col("v")).with_name("sv"))
    df = agg.with_window_column("r", F.sum(F.col("sv")))
    out = df.to_pandas()
    assert len(out) == 5 and np.allclose(out["r"], out["sv"].sum())


# ---------------------------------------------------------------------------
# union-of-aggregates single-pass rewrite (the q28 shape; ref
# RewriteDistinctAggregates' Expand plan + GpuAggregateExec merge)
# ---------------------------------------------------------------------------

def test_union_agg_single_pass_plan_shape():
    """q28 must plan as ONE aggregation pipeline (no Union of 6 scans)."""
    s = tpu_session()
    ss, _, _ = _dstables(s)
    tree = tpcds.q28(ss, F)._physical().tree_string()
    assert "Union" not in tree, tree
    assert tree.count("InMemoryScan") == 1, tree


def test_union_agg_overlapping_branches():
    """Non-disjoint branch filters take the Expand path: a row matching
    two branches must count in both."""
    import pyarrow as pa
    t = pa.table({"q": pa.array([1, 5, 10, 15, 20], pa.int64()),
                  "v": pa.array([1.0, 2.0, 3.0, 4.0, 5.0])})

    def q(s):
        df = s.create_dataframe(t)
        b1 = df.filter((F.col("q") >= F.lit(0)) & (F.col("q") <= F.lit(10)))
        b2 = df.filter((F.col("q") >= F.lit(5)) & (F.col("q") <= F.lit(20)))
        return (b1.agg(F.count(F.col("v")).with_name("c"),
                       F.sum(F.col("v")).with_name("s"),
                       F.count_distinct(F.col("v")).with_name("cd"))
                .union(b2.agg(F.count(F.col("v")).with_name("c"),
                              F.sum(F.col("v")).with_name("s"),
                              F.count_distinct(F.col("v")).with_name("cd"))))
    t_got = assert_tpu_and_cpu_equal(q, ignore_order=False)
    assert list(t_got["c"]) == [3, 4]


def test_union_agg_empty_branch_defaults():
    """A branch matching zero rows must still emit its row: count 0,
    sum/avg NULL (empty-aggregate semantics through the left join)."""
    import pyarrow as pa
    t = pa.table({"q": pa.array([1, 2, 3], pa.int64()),
                  "v": pa.array([1.0, 2.0, 3.0])})

    def q(s):
        df = s.create_dataframe(t)
        b1 = df.filter((F.col("q") >= F.lit(0)) & (F.col("q") <= F.lit(10)))
        b2 = df.filter((F.col("q") >= F.lit(100))
                       & (F.col("q") <= F.lit(200)))
        aggs = lambda b: b.agg(F.count(F.col("v")).with_name("c"),
                               F.avg(F.col("v")).with_name("a"),
                               F.count_distinct(F.col("v")).with_name("cd"))
        return aggs(b1).union(aggs(b2))
    t_got = assert_tpu_and_cpu_equal(q, ignore_order=False,
                                     approximate_float=True)
    assert list(t_got["c"]) == [3, 0]
    assert t_got["a"].isna().tolist() == [False, True]


def test_union_agg_branch_order_preserved():
    """Union output rows arrive in branch order even though the single
    pass computes them keyed by branch id."""
    import pyarrow as pa
    t = pa.table({"q": pa.array(list(range(30)), pa.int64())})

    def q(s):
        df = s.create_dataframe(t)
        outs = None
        for lo, hi in [(20, 29), (0, 9), (10, 19)]:
            b = df.filter((F.col("q") >= F.lit(lo))
                          & (F.col("q") <= F.lit(hi))) \
                .agg(F.min(F.col("q")).with_name("mn"),
                     F.max(F.col("q")).with_name("mx"))
            outs = b if outs is None else outs.union(b)
        return outs
    t_got = assert_tpu_and_cpu_equal(q, ignore_order=False)
    assert list(t_got["mn"]) == [20, 0, 10]
