"""Tracing + profiling subsystem (trace/ + tools/profile, ISSUE 4).

Covers the tracer core (nesting, truncation, the disabled path being a
no-op), single-process query traces, the 3-worker distributed
trace-merge (driver + every worker on one timeline), and golden output
of the profile analyzer over a checked-in fixture trace."""
import json
import os

import numpy as np
import pyarrow as pa
import pytest

from harness import tpu_session
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.trace import (Tracer, active_tracer, chrome_trace,
                                    install_tracer, load_chrome_trace,
                                    write_chrome_trace)

pytestmark = pytest.mark.filterwarnings("ignore::ResourceWarning")

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


# ---------------------------------------------------------------------------
# core
# ---------------------------------------------------------------------------

def test_span_nesting_parent_ids():
    tr = Tracer()
    with tr.span("outer"):
        with tr.span("inner"):
            pass
        with tr.span("inner2"):
            pass
    evs = tr.snapshot()
    by_name = {e["name"]: e for e in evs}
    assert by_name["outer"]["parent"] == 0
    assert by_name["inner"]["parent"] == by_name["outer"]["id"]
    assert by_name["inner2"]["parent"] == by_name["outer"]["id"]
    # children's intervals are contained in the parent's
    o = by_name["outer"]
    for c in ("inner", "inner2"):
        assert by_name[c]["ts"] >= o["ts"]
        assert (by_name[c]["ts"] + by_name[c]["dur"]
                <= o["ts"] + o["dur"])


def test_span_records_on_exception():
    tr = Tracer()
    with pytest.raises(ValueError):
        with tr.span("boom"):
            raise ValueError("x")
    assert [e["name"] for e in tr.snapshot()] == ["boom"]


def test_ring_buffer_truncation_counts_drops():
    tr = Tracer(max_events=16)
    for i in range(40):
        tr.instant(f"e{i}")
    evs = tr.snapshot()
    assert len(evs) == 16
    assert tr.dropped == 24
    # OLDEST events were dropped
    assert evs[0]["name"] == "e24" and evs[-1]["name"] == "e39"
    doc = chrome_trace(tr)
    assert doc["otherData"]["dropped_events"] == 24


def test_disabled_path_records_nothing():
    """With tracing off (the default) no tracer exists, instrumented
    sites see None and skip, and a full query leaves no global state."""
    assert active_tracer() is None
    t = pa.table({"k": pa.array(np.arange(500) % 5),
                  "v": pa.array(np.arange(500, dtype=np.float64))})
    s = tpu_session()
    out = (s.create_dataframe(t).group_by("k")
           .agg(F.sum(F.col("v")).with_name("sv"))).collect_arrow()
    assert out.num_rows == 5
    assert active_tracer() is None     # conf off -> never installed


def test_disabled_overhead_is_one_branch():
    """The record path when disabled is a module-global load + branch:
    time a tight loop over the exact site pattern and assert it stays
    within an order of magnitude of a bare loop (a generous bound —
    this guards against accidentally adding allocation/conf lookups to
    the disabled path, not against scheduler noise)."""
    import time
    from spark_rapids_tpu.trace import core as trace_core
    assert trace_core.TRACER is None
    n = 200_000

    def site_loop():
        acc = 0
        for _ in range(n):
            tr = trace_core.TRACER          # the instrumented pattern
            if tr is not None:
                tr.instant("x")             # pragma: no cover
            acc += 1
        return acc

    def bare_loop():
        acc = 0
        for _ in range(n):
            acc += 1
        return acc

    t0 = time.perf_counter(); site_loop(); site = time.perf_counter() - t0
    t0 = time.perf_counter(); bare_loop(); bare = time.perf_counter() - t0
    assert site < max(10 * bare, bare + 0.5), (site, bare)


def test_ingest_aligns_remote_clock_and_lanes():
    a, b = Tracer(), Tracer()
    b.proc_name = "worker-7"
    b.proc_names[b.pid] = "worker-7"
    b.epoch_ns = a.epoch_ns + 5_000_000_000   # worker clock 5s ahead
    t0 = b.now()
    b.complete("remote", t0, t0 + 1000)
    a.ingest(b.serialize())
    evs = a.snapshot()
    assert len(evs) == 1
    # the remote span was shifted onto A's monotonic timeline
    assert evs[0]["ts"] == t0 + 5_000_000_000
    assert a.proc_names[b.pid] == "worker-7"
    assert len(b.snapshot()) == 0              # serialize() drains


# ---------------------------------------------------------------------------
# single-process query trace
# ---------------------------------------------------------------------------

def test_query_trace_written_and_loadable(tmp_path):
    out_path = str(tmp_path / "q.json")
    t = pa.table({"k": pa.array(np.arange(2000) % 7),
                  "v": pa.array(np.arange(2000, dtype=np.float64))})
    s = tpu_session({"spark.rapids.tpu.trace.enabled": True,
                     "spark.rapids.tpu.trace.output": out_path})
    df = (s.create_dataframe(t).group_by("k")
          .agg(F.sum(F.col("v")).with_name("sv")))
    assert df.collect_arrow().num_rows == 7
    events = load_chrome_trace(out_path)
    phases = {e.get("ph") for e in events}
    assert "X" in phases and "M" in phases
    names = {e["name"] for e in events if e.get("ph") == "X"}
    assert "query" in names
    assert any(n.endswith("Exec") for n in names), names
    assert any(n.startswith("h2d.") for n in names), names
    # valid chrome trace: every X event has the required keys
    for e in events:
        if e.get("ph") == "X":
            assert {"name", "ts", "dur", "pid", "tid"} <= set(e)
    install_tracer(None)


# ---------------------------------------------------------------------------
# distributed: 3 workers, one merged timeline
# ---------------------------------------------------------------------------

def test_three_worker_trace_merge(tmp_path):
    from spark_rapids_tpu.config import TpuConf
    from spark_rapids_tpu.shuffle.cluster import LocalCluster
    out_path = str(tmp_path / "dist.json")
    conf = TpuConf({"spark.rapids.tpu.trace.enabled": True,
                    "spark.rapids.tpu.trace.output": out_path})
    cl = LocalCluster(3, conf=conf)
    try:
        rng = np.random.RandomState(5)
        t = pa.table({"k": pa.array(rng.randint(0, 13, 9000)),
                      "v": pa.array(rng.uniform(0, 100, 9000))})
        s = tpu_session()
        df = (s.create_dataframe(t).group_by("k")
              .agg(F.sum(F.col("v")).with_name("sv"),
                   F.count_star().with_name("n")))
        got = cl.execute(df).to_pandas().sort_values("k") \
                .reset_index(drop=True)
        want = df.collect_arrow().to_pandas().sort_values("k") \
                 .reset_index(drop=True)
        np.testing.assert_allclose(got["sv"], want["sv"], rtol=1e-9)
    finally:
        cl.shutdown()
        install_tracer(None)
    events = load_chrome_trace(out_path)
    # one coherent timeline: the driver AND every worker have a lane
    lane_names = {e["args"]["name"] for e in events
                  if e.get("ph") == "M"
                  and e.get("name") == "process_name"}
    assert {"worker-0", "worker-1", "worker-2"} <= lane_names, lane_names
    assert "driver" in lane_names
    pids = {e["pid"] for e in events if e.get("ph") == "X"}
    assert len(pids) >= 4          # driver + 3 worker processes
    names = {e["name"] for e in events if e.get("ph") == "X"}
    assert "cluster.execute" in names
    assert any(n.startswith("task:") for n in names), names
    assert any(n.startswith("rpc:") for n in names), names
    assert "shuffle.put" in names
    # worker spans were shifted onto the driver timeline: everything
    # falls inside the cluster.execute umbrella (loose 10s slack for
    # clock-alignment jitter)
    umb = next(e for e in events if e["name"] == "cluster.execute")
    lo, hi = umb["ts"] - 10e6, umb["ts"] + umb["dur"] + 10e6
    for e in events:
        if e.get("ph") == "X":
            assert lo <= e["ts"] <= hi, (e["name"], e["ts"], (lo, hi))
    # the analyzer runs over the merged artifact without error and
    # reports every required section
    from spark_rapids_tpu.tools.profile import analyze_file
    analysis, report = analyze_file(out_path)
    assert "== Top operators by self time ==" in report
    assert "== Memory pressure ==" in report
    assert "== Shuffle partitions ==" in report
    assert analysis["shuffle"]["shuffles"], "no shuffle sizes collected"
    assert {"worker-0", "worker-1", "worker-2"} <= set(analysis["workers"])


# ---------------------------------------------------------------------------
# analyzer golden output
# ---------------------------------------------------------------------------

def test_profile_analyzer_golden():
    fixture = os.path.join(FIXTURES, "trace_fixture.json")
    golden = os.path.join(FIXTURES, "profile_golden.txt")
    from spark_rapids_tpu.tools.profile import analyze, format_report
    events = load_chrome_trace(fixture)
    report = format_report(analyze(events), source="trace_fixture.json")
    with open(golden) as f:
        assert report == f.read()


def test_profile_analyzer_self_time_math():
    from spark_rapids_tpu.tools.profile import self_times
    events = [
        {"ph": "X", "name": "parent", "cat": "exec", "ts": 0,
         "dur": 100, "pid": 1, "tid": 1},
        {"ph": "X", "name": "child", "cat": "exec", "ts": 10,
         "dur": 30, "pid": 1, "tid": 1},
        {"ph": "X", "name": "child", "cat": "exec", "ts": 50,
         "dur": 20, "pid": 1, "tid": 1},
        # different lane: no nesting against pid 1
        {"ph": "X", "name": "parent", "cat": "exec", "ts": 20,
         "dur": 40, "pid": 2, "tid": 1},
    ]
    st = self_times(events)
    assert st["parent"]["count"] == 2
    assert st["parent"]["total_us"] == 140
    assert st["parent"]["self_us"] == 90     # 100 - 30 - 20, + 40
    assert st["child"]["self_us"] == 50


def test_profile_cli_main(tmp_path, capsys):
    from spark_rapids_tpu.tools.profile import main
    fixture = os.path.join(FIXTURES, "trace_fixture.json")
    assert main([fixture]) == 0
    out = capsys.readouterr().out
    assert "Recommendations" in out
    assert main([fixture, "--json"]) == 0
    json.loads(capsys.readouterr().out)      # valid JSON mode


def test_write_and_reload_roundtrip(tmp_path):
    tr = Tracer()
    with tr.span("a", cat="exec", args={"k": 1}):
        tr.counter("c", {"v": 2.0})
    p = write_chrome_trace(str(tmp_path / "t.json"), tr)
    evs = load_chrome_trace(p)
    assert {e["ph"] for e in evs} == {"M", "X", "C"}
    x = next(e for e in evs if e["ph"] == "X")
    assert x["args"] == {"k": 1}
