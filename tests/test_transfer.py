"""H2D ingest compression (columnar/transfer.py): encodings must be
bit-exact, chosen only when provably lossless, and transparent to every
engine path (device kernels read decoded arrays identical to the raw
transfer's)."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import ColumnarBatch
from spark_rapids_tpu.columnar import transfer


@pytest.fixture(autouse=True)
def _force_encoding(monkeypatch):
    monkeypatch.setattr(transfer, "MIN_RAW_BYTES", 0)


def _roundtrip(table):
    b = ColumnarBatch.from_arrow(table)
    for c in b.columns:           # bypass host mirrors: force real D2H
        c.host_mirror = None
    back = b.to_arrow()
    for name in table.column_names:
        a0 = table.column(name).combine_chunks()
        a1 = back.column(name).combine_chunks()
        if a1.type != a0.type:
            a1 = a1.cast(a0.type)
        n0 = np.asarray(a0.is_null())
        np.testing.assert_array_equal(n0, np.asarray(a1.is_null()),
                                      err_msg=name)
        fill = False if pa.types.is_boolean(a0.type) else 0
        v0 = a0.fill_null(fill).to_numpy(zero_copy_only=False)
        v1 = a1.fill_null(fill).to_numpy(zero_copy_only=False)
        if np.issubdtype(np.asarray(v0).dtype, np.floating):
            # bit-exact incl. NaN/inf (arrow equals() is NaN-hostile)
            np.testing.assert_array_equal(
                np.asarray(v0).view(np.int64)[~n0],
                np.asarray(v1).view(np.int64)[~n0], err_msg=name)
        else:
            np.testing.assert_array_equal(v0[~n0], v1[~n0], err_msg=name)
    return b


def test_tpc_shaped_columns_encode_and_roundtrip():
    rng = np.random.RandomState(0)
    n = 4000
    nulls = rng.rand(n) < 0.1
    disc = np.round(rng.randint(0, 11, n) / 100.0, 2)
    t = pa.table({
        "price": pa.array(np.round(rng.uniform(900.0, 105000.0, n), 2)),
        "qty": pa.array(rng.randint(1, 51, n).astype(np.float64)),
        "disc": pa.array(np.where(nulls, np.nan, disc), mask=nulls),
        "raw_f": pa.array(rng.standard_normal(n)),
        "d": pa.array((np.datetime64("1992-01-01")
                       + rng.randint(0, 2526, n)).astype("datetime64[D]")),
        "b": pa.array(rng.rand(n) > 0.5),
        "i": pa.array(rng.randint(-5, 300, n)),
        "big": pa.array(rng.randint(-2**62, 2**62, n)),
    })
    b = _roundtrip(t)
    pairs = [(np.asarray(c.data), np.asarray(c.validity))
             for c in b.columns]
    flat, specs, params, ratio, rb = transfer.encode_columns(pairs)
    kinds = [s[0][0] for s in specs]
    # floats NEVER narrow: the TPU backend's emulated f64 cannot
    # reproduce division or int->f64 conversion bits, so any
    # value-recomputing float decode would corrupt band-edge comparisons
    assert kinds[0] == "raw"            # 2-decimal price stays raw
    assert kinds[1] == "raw"            # even integral doubles stay raw
    assert kinds[3] == "raw"            # full-entropy floats stay raw
    assert kinds[4] == "int_off"        # dates narrow to uint16
    assert kinds[5] == "bool_bits"
    assert kinds[7] == "raw"            # 63-bit ints cannot narrow
    assert ratio < 0.8


def test_all_null_and_empty_columns():
    t = pa.table({
        "an": pa.array([None] * 100, pa.float64()),
        "v": pa.array(np.arange(100, dtype=np.int64)),
    })
    _roundtrip(t)


def test_special_floats_stay_raw():
    vals = np.array([1.0, np.nan, np.inf, -np.inf, 2.25])
    t = pa.table({"f": pa.array(vals)})
    b = _roundtrip(t)
    pairs = [(np.asarray(c.data), np.asarray(c.validity))
             for c in b.columns]
    _, specs, _, _, _ = transfer.encode_columns(pairs)
    assert specs[0][0] == ("raw",)


def test_encoded_batch_feeds_device_kernels():
    """Aggregation over an encoded-ingest batch must equal the oracle."""
    from harness import assert_tpu_and_cpu_equal
    from spark_rapids_tpu.api import functions as F
    rng = np.random.RandomState(1)
    n = 3000
    t = pa.table({"k": pa.array(rng.randint(0, 5, n)),
                  "v": pa.array(np.round(rng.uniform(0, 100, n), 2))})

    def q(s):
        return s.create_dataframe(t).group_by("k").agg(
            F.sum(F.col("v")).with_name("s"),
            F.count_star().with_name("c"))
    assert_tpu_and_cpu_equal(q, approximate_float=True)


def test_f64_passthrough_serves_exact_source_bits():
    """r4 regression (TPC-H q6 wrong by 28%): the backend's emulated f64
    carries ~48 mantissa bits, so ANY materialization of an untouched
    ingested column must serve the host-mirror source bits — both at the
    batch level and through column-level to_arrow (the host engine's
    ColumnRef.eval_host path, where `discount >= 0.05` silently dropped
    every boundary row)."""
    import pyarrow as pa
    from spark_rapids_tpu.columnar import ColumnarBatch
    from spark_rapids_tpu.exprs.base import ColumnRef
    vals = [0.05000000000000000277, 0.25, 1.0 / 3.0, 1e-300, None]
    t = pa.table({"d": pa.array(vals, type=pa.float64())})
    b = ColumnarBatch.from_arrow(t)
    got_col = ColumnRef("d").eval_host(b)
    got_batch = b.to_arrow().column("d")
    for got in (got_col, got_batch):
        for g, w in zip(got, t.column("d")):
            assert g.as_py() == w.as_py(), (g, w)
            if g.as_py() is not None:
                assert g.as_py().hex() == w.as_py().hex()


def test_f64_host_engine_boundary_comparison_exact():
    """End-to-end: host-engine filter on an exact decimal boundary keeps
    boundary rows (differential vs pandas)."""
    import numpy as np
    import pyarrow as pa
    from harness import cpu_session, tpu_session
    from spark_rapids_tpu.api import functions as F
    rng = np.random.RandomState(3)
    d = np.round(rng.randint(0, 11, 20000) * 0.01, 2)
    t = pa.table({"d": pa.array(d), "v": pa.array(rng.rand(20000))})
    want = int((d >= 0.05).sum())
    for s in (tpu_session(), cpu_session()):
        got = s.create_dataframe(t).filter(
            F.col("d") >= F.lit(0.05)).count()
        assert got == want, (got, want)
