"""UDF compiler + runtime (ref udf-compiler/ CatalystExpressionBuilder,
GpuUserDefinedFunction/RapidsUDF)."""
import math

import pandas as pd
import pytest

from harness import assert_tpu_and_cpu_equal, tpu_session
from data_gen import DoubleGen, IntGen, gen_df
from spark_rapids_tpu.api import functions as F


def _df(s, n=256):
    return s.create_dataframe(gen_df(
        {"a": IntGen(lo=-50, hi=50, nullable=False),
         "b": DoubleGen(nullable=False)}, n=n))


# ---------------------------------------------------------------------------
# bytecode compilation
# ---------------------------------------------------------------------------

def test_udf_compiles_arithmetic():
    u = F.udf(lambda x, y: x * 2 + y - 1)
    expr = u(F.col("a"), F.col("b"))
    assert u.last_compiled is True
    def q(s):
        return _df(s).with_column("c", u(F.col("a"), F.col("b")))
    assert_tpu_and_cpu_equal(q, approximate_float=True)


def test_udf_compiles_ternary():
    u = F.udf(lambda x: x if x > 0 else -x)
    u(F.col("a"))
    assert u.last_compiled is True
    def q(s):
        return _df(s).with_column("c", u(F.col("a")))
    assert_tpu_and_cpu_equal(q)


def test_udf_compiles_nested_if():
    def f(x):
        if x > 10:
            return 2
        elif x > 0:
            return 1
        else:
            return 0
    u = F.udf(f)
    u(F.col("a"))
    assert u.last_compiled is True
    def q(s):
        return _df(s).with_column("c", u(F.col("a")))
    assert_tpu_and_cpu_equal(q)


def test_udf_compiles_math_calls():
    u = F.udf(lambda x: math.sqrt(abs(x)) + math.log(abs(x) + 1.0))
    u(F.col("b"))
    assert u.last_compiled is True
    def q(s):
        return _df(s).with_column("c", u(F.col("b")))
    assert_tpu_and_cpu_equal(q, approximate_float=True)


def test_udf_compiles_min_max():
    u = F.udf(lambda x, y: min(x, y) + max(x, y))
    u(F.col("a"), F.col("a"))
    assert u.last_compiled is True
    def q(s):
        return _df(s).with_column("c", u(F.col("a"), F.col("a")))
    assert_tpu_and_cpu_equal(q)


def test_udf_compiles_local_variables():
    def f(x, y):
        t = x * 2
        return t + y
    u = F.udf(f)
    u(F.col("a"), F.col("a"))
    assert u.last_compiled is True
    def q(s):
        return _df(s).with_column("c", u(F.col("a"), F.col("a")))
    assert_tpu_and_cpu_equal(q)


def test_udf_closure_constant():
    k = 7
    u = F.udf(lambda x: x + k)
    expr = u(F.col("a"))
    assert u.last_compiled is True
    def q(s):
        return _df(s).with_column("c", u(F.col("a")))
    assert_tpu_and_cpu_equal(q)


# ---------------------------------------------------------------------------
# fallback path
# ---------------------------------------------------------------------------

def test_udf_while_loop_falls_back_row_based():
    # py3.10 compiles the back-edge to JUMP_ABSOLUTE — must reject,
    # not follow it (the tracer would spin forever)
    def f(x):
        t = 0
        while t < 3 * x:
            t += x
        return t
    u = F.udf(f)
    u(F.col("a"))
    assert u.last_compiled is False


def test_udf_loop_falls_back_row_based():
    def f(x):
        t = 0
        for i in range(3):
            t += x
        return t
    u = F.udf(f)
    u(F.col("a"))
    assert u.last_compiled is False
    s = tpu_session()
    out = _df(s).with_column("c", u(F.col("a"))).to_pandas()
    assert (out["c"] == out["a"] * 3).all()


def test_udf_fallback_marked_in_explain():
    u = F.udf(lambda x: hash(x))   # unknown call -> fallback
    u(F.col("a"))
    assert u.last_compiled is False
    s = tpu_session()
    df = _df(s).with_column("c", u(F.col("a")))
    txt = df.explain("potential")
    assert "PythonUDF" in txt or "host" in txt


def test_udf_compiler_disable_conf():
    u = F.udf(lambda x: x + 1, compile=False)
    u(F.col("a"))
    assert u.last_compiled is False


# ---------------------------------------------------------------------------
# columnar device UDF (RapidsUDF analog)
# ---------------------------------------------------------------------------

def test_columnar_udf_runs_on_device():
    import jax.numpy as jnp
    from spark_rapids_tpu.udf import TpuUDF
    from spark_rapids_tpu.exprs.base import DVal
    from spark_rapids_tpu.types import FLOAT64

    class Sigmoid(TpuUDF):
        return_type = FLOAT64

        def evaluate_columnar(self, x: DVal) -> DVal:
            return DVal(1.0 / (1.0 + jnp.exp(-x.data.astype(jnp.float64))),
                        x.validity, FLOAT64)

    s = tpu_session()
    df = _df(s).with_column("c", F.columnar_udf(Sigmoid(), F.col("b")))
    out = df.to_pandas()
    import numpy as np
    np.testing.assert_allclose(out["c"], 1 / (1 + np.exp(-out["b"])),
                               rtol=1e-12)


def test_df_udf_inlines_into_device_plan():
    """ref DFUDFPlugin: a UDF defined as Column expressions runs fully on
    device with no fallback tagging."""
    import pyarrow as pa
    from harness import tpu_session, assert_all_on_tpu
    from spark_rapids_tpu.api import functions as F

    @F.df_udf
    def gross(price, tax):
        return price * (F.lit(1.0) + tax)

    def q(s):
        df = s.create_dataframe(
            pa.table({"p": [10.0, 20.0], "t": [0.1, 0.2]}))
        return df.select(gross(F.col("p"), F.col("t")).alias("g"))
    assert_all_on_tpu(q)
    s = tpu_session()
    out = q(s)
    assert [r["g"] for r in out.collect()] == [11.0, 24.0]
