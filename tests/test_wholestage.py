"""Whole-stage fusion + executable cache (ISSUE 6).

Fusion correctness battery: fused regions must be byte-identical to the
per-operator pipeline across the filter/project/agg/join/sort/window/
string suites, with and without injected OOM retries/splits mid-stage.
Cache-key tests cover digest/dtype/extra miss cases and corrupt
persistent entries; the disabled path must cost nothing (the
trace/metrics off-path contract).
"""
import os

import numpy as np
import pandas as pd
import pytest

from harness import tpu_session
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.plan import exec_cache

FUSION_OFF = {"spark.rapids.tpu.fusion.enabled": False}


def _table(n=2000, seed=7):
    rng = np.random.RandomState(seed)
    return pd.DataFrame({
        "a": rng.randint(0, 100, n),
        "b": rng.uniform(-10, 10, n),
        "c": rng.randint(0, 5, n),
        "s": np.asarray([f"key-{i % 7:02d}" for i in range(n)],
                        dtype=object),
    })


def _chain(df):
    """A 3-op fusible region: filter -> project -> filter."""
    return (df.filter(F.col("a") > 10)
            .select((F.col("a") * 2).alias("a2"),
                    (F.col("b") + 1.5).alias("b1"),
                    F.col("c"), F.col("s"))
            .filter(F.col("a2") < 150))


QUERIES = {
    "plain": lambda df: _chain(df),
    "agg": lambda df: (_chain(df).group_by("c")
                       .agg(F.sum(F.col("b1")).with_name("sb"),
                            F.count_star().with_name("n"))
                       .order_by("c")),
    "sort": lambda df: _chain(df).order_by("a2", "c"),
    "strings": lambda df: (_chain(df).group_by("s")
                           .agg(F.max(F.col("a2")).with_name("m"))
                           .order_by("s")),
}


@pytest.mark.parametrize("name", sorted(QUERIES))
def test_fused_matches_unfused(name):
    q = QUERIES[name]
    fused = q(tpu_session().create_dataframe(_table())).collect_arrow()
    plain = q(tpu_session(FUSION_OFF)
              .create_dataframe(_table())).collect_arrow()
    assert fused.equals(plain), f"{name}: fused result diverged"


def test_fused_join_and_window_match_unfused():
    left = _table(500, seed=1)
    right = pd.DataFrame({"c": np.arange(5), "w": np.arange(5) * 10.0})

    def q(s):
        df = _chain(s.create_dataframe(left))
        other = s.create_dataframe(right)
        j = df.join(other, on="c", how="inner")
        from spark_rapids_tpu.exprs import ColumnRef
        from spark_rapids_tpu.exprs.aggregates import Sum
        return j.with_window_column(
            "ws", Sum(ColumnRef("b1")), partition_by=["c"],
            order_by=[F.col("a2").asc()], frame=("rows", -1, 0))

    fused = q(tpu_session()).to_pandas()
    plain = q(tpu_session(FUSION_OFF)).to_pandas()
    key = ["c", "a2", "b1"]
    fused = fused.sort_values(key, kind="mergesort").reset_index(drop=True)
    plain = plain.sort_values(key, kind="mergesort").reset_index(drop=True)
    pd.testing.assert_frame_equal(fused, plain)


def test_fused_plan_is_visible_in_explain_and_trace():
    from spark_rapids_tpu.trace import Tracer, install_tracer
    s = tpu_session()
    q = _chain(s.create_dataframe(_table()))
    out = q.explain("physical")
    assert "WholeStage[fused=[" in out
    tr = Tracer()
    install_tracer(tr)
    try:
        q.collect_arrow()
        spans = [e for e in tr.snapshot()
                 if e.get("name") == "WholeStageExec"]
        assert spans, "no WholeStageExec span in the trace"
        assert spans[0]["args"].get("fused"), "span lost the fused=[...] arg"
    finally:
        install_tracer(None)


def test_explain_analyze_reports_per_op_rows_inside_fusion():
    s = tpu_session()
    out = _chain(s.create_dataframe(_table())).explain("analyze")
    assert "WholeStage[fused=[" in out
    # per-operator breakdown lines survive fusion, with exact rows
    assert "+ Filter[(a > 10)]" in out
    assert "+ Project[" in out
    for line in out.splitlines():
        if line.strip().startswith("+ "):
            assert "rows=" in line and "self=" in line


def test_fused_survives_injected_retry_oom():
    s = tpu_session()
    df = s.create_dataframe(_table(4096), num_partitions=4)
    q = (_chain(df).group_by("c")
         .agg(F.sum(F.col("b1")).with_name("sb")).order_by("c"))
    expect = (QUERIES["plain"](tpu_session(FUSION_OFF)
                               .create_dataframe(_table(4096)))
              .to_pandas().groupby("c")["b1"].sum())
    mm = s.exec_context().memory
    mm.force_retry_oom(1)
    try:
        got = q.to_pandas()
    finally:
        mm.clear_injections()
    np.testing.assert_allclose(
        got.set_index("c")["sb"].to_numpy(),
        expect.to_numpy(), rtol=1e-9)


def test_fused_survives_injected_split_mid_stage():
    """SplitAndRetryOOM mid-stage halves the input batch and re-runs the
    fused kernel over each piece: the concatenated pieces must be
    byte-identical to the unsplit run (the retry framework's idempotence
    contract applied to a fused region)."""
    import pyarrow as pa
    from spark_rapids_tpu.exec.wholestage import WholeStageExec
    from spark_rapids_tpu.mem import SpillableBatch, with_retry
    s = tpu_session()
    physical = _chain(s.create_dataframe(_table()))._physical()
    node = physical
    while not isinstance(node, WholeStageExec):
        node = node.children[0]
    ctx = s.exec_context()
    ref = pa.concat_tables(
        [node._run_fused(b.ensure_device())[0].to_arrow()
         for b in node.children[0].execute(ctx)])
    mm = ctx.memory
    splits = []

    def fn(sb):
        mm.reserve(8)                 # injected split fires here
        mm.release(8)
        splits.append(1)
        try:
            return node._run_fused(sb.get().ensure_device())[0].to_arrow()
        finally:
            sb.close()                # fn owns the consumed input

    pieces = [SpillableBatch(b.ensure_device(), mm)
              for b in node.children[0].execute(ctx)]
    mm.force_split_and_retry_oom(1)
    try:
        tabs = list(with_retry(pieces, fn, mm))
    finally:
        mm.clear_injections()
    assert len(tabs) > 1, "the injected split never fired"
    assert pa.concat_tables(tabs).equals(ref)


# ---------------------------------------------------------------------------
# executable cache
# ---------------------------------------------------------------------------

def test_warm_repeat_hits_cache_with_zero_compile():
    def run():
        s = tpu_session()
        return _chain(s.create_dataframe(_table())).collect_arrow()
    run()                                     # cold: builds the kernel
    st0 = exec_cache.stats()
    warm = run()                              # fresh session, same shape
    st1 = exec_cache.stats()
    assert st1["misses"] == st0["misses"], "warm repeat rebuilt a kernel"
    assert st1["hits"] > st0["hits"]
    assert st1["compile_s"] == st0["compile_s"], \
        "warm repeat paid XLA compile"
    assert warm.num_rows > 0


def test_cache_key_miss_cases():
    k1 = exec_cache.fused_key("digest-a", (("a", "bigint"),))
    k2 = exec_cache.fused_key("digest-b", (("a", "bigint"),))
    k3 = exec_cache.fused_key("digest-a", (("a", "double"),))
    k4 = exec_cache.fused_key("digest-a", (("a", "bigint"),), extra=(64,))
    assert len({k1, k2, k3, k4}) == 4
    # device kind is part of every key
    assert k1[2] == exec_cache.device_kind()
    # digest is stable and input-sensitive
    assert exec_cache.digest_of("x", "y") == exec_cache.digest_of("x", "y")
    assert exec_cache.digest_of("x", "y") != exec_cache.digest_of("xy")


def test_get_or_build_hit_and_miss_accounting():
    st0 = exec_cache.stats()
    key = exec_cache.fused_key("test-" + os.urandom(4).hex(), ())
    built = []

    def build():
        built.append(1)
        return lambda: 42
    fn1 = exec_cache.get_or_build(key, build)
    fn2 = exec_cache.get_or_build(key, build)
    assert fn1 is fn2 and len(built) == 1
    st1 = exec_cache.stats()
    assert st1["misses"] == st0["misses"] + 1
    assert st1["hits"] == st0["hits"] + 1


def test_corrupt_persistent_entry_falls_back_to_recompile(tmp_path):
    """Garbage in the persistent tier must never fail a query: entries
    jax cannot deserialize are recompiled, and the size trim tolerates
    unreadable files."""
    cache_dir = str(tmp_path / "xla_cache")
    os.makedirs(cache_dir)
    with open(os.path.join(cache_dir, "corrupt-entry"), "wb") as f:
        f.write(b"\x00not an executable\xff" * 64)
    s = tpu_session({"spark.rapids.tpu.compile.cache.dir": cache_dir})
    t = _chain(s.create_dataframe(_table())).collect_arrow()
    plain = _chain(tpu_session(FUSION_OFF)
                   .create_dataframe(_table())).collect_arrow()
    assert t.equals(plain)
    # trim walks the corrupt file without raising
    assert exec_cache.trim_persistent(cache_dir, 1) >= 1


def test_compile_cache_dir_not_sticky_across_sessions(tmp_path):
    """A session with an EMPTY compile.cache.dir conf must get the
    process default back — not the previous session's override."""
    import jax
    from spark_rapids_tpu.config import TpuConf
    exec_cache.configure_from_conf(TpuConf())   # settle on the default
    default = jax.config.jax_compilation_cache_dir or ""
    override = str(tmp_path / "session_cache")
    exec_cache.configure_from_conf(
        TpuConf({"spark.rapids.tpu.compile.cache.dir": override}))
    assert jax.config.jax_compilation_cache_dir == override
    exec_cache.configure_from_conf(TpuConf())
    assert (jax.config.jax_compilation_cache_dir or "") == default


def test_trim_persistent_evicts_oldest_first(tmp_path):
    d = str(tmp_path / "cache")
    os.makedirs(d)
    for i in range(4):
        with open(os.path.join(d, f"e{i}"), "wb") as f:
            f.write(b"x" * 100)
        os.utime(os.path.join(d, f"e{i}"), (i + 1, i + 1))
    removed = exec_cache.trim_persistent(d, 250)
    assert removed == 2
    assert sorted(os.listdir(d)) == ["e2", "e3"]
    assert exec_cache.trim_persistent(d, 1000) == 0


def test_disabled_path_is_zero_overhead():
    """With fusion off the pass must return before walking the tree —
    the one-branch-when-off contract shared with trace/metrics."""
    from spark_rapids_tpu.config import TpuConf
    from spark_rapids_tpu.exec.wholestage import fuse_whole_stages

    class Untouchable:
        @property
        def children(self):          # pragma: no cover - must not run
            raise AssertionError("disabled fusion pass walked the tree")
    node = Untouchable()
    conf = TpuConf(FUSION_OFF)
    assert fuse_whole_stages(node, conf) is node
    s = tpu_session(FUSION_OFF)
    out = _chain(s.create_dataframe(_table())).explain("physical")
    assert "WholeStage" not in out


def test_rect_chain_kernel_is_process_wide():
    """The byte-rectangle string kernels must resolve through the
    executable cache (per-exec dicts re-traced every query — the
    string_transforms_100k warm cliff)."""
    from spark_rapids_tpu.api.functions import col, upper
    from spark_rapids_tpu.exprs.compiler import compile_rect_chain
    e = upper(col("s")).expr
    fn1 = compile_rect_chain(e, 16, 1024, 64)
    fn2 = compile_rect_chain(e, 16, 1024, 64)
    assert fn1 is fn2
    assert compile_rect_chain(e, 32, 1024, 64) is not fn1


# ---------------------------------------------------------------------------
# cost-model feedback + placement reason
# ---------------------------------------------------------------------------

def test_fused_stage_walls_feed_the_cost_model(monkeypatch):
    from spark_rapids_tpu.plan import cost
    monkeypatch.setitem(cost._OP_COSTS, ("WholeStageExec", "device"),
                        (1 << 20, 0.001))
    lc = cost.learned_row_cost("WholeStageExec", "device")
    assert lc is not None and lc < 1e-8
    # under the min-rows threshold the learned cost is not trusted
    monkeypatch.setitem(cost._OP_COSTS, ("tiny", "device"), (10, 5.0))
    assert cost.learned_row_cost("tiny", "device") is None


def test_op_costs_persist_roundtrip(tmp_path, monkeypatch):
    import importlib
    from spark_rapids_tpu.plan import stats_store
    monkeypatch.setenv("SRTPU_STATS_PERSIST", "1")
    monkeypatch.setenv("SRTPU_STATS_PATH", str(tmp_path / "stats.json"))
    from spark_rapids_tpu.plan import cost
    monkeypatch.setattr(stats_store, "_loaded", False)
    monkeypatch.setattr(stats_store, "_dirty", True)
    monkeypatch.setitem(cost._OP_COSTS, ("WholeStageExec", "device"),
                        (123456, 0.5))
    stats_store.save()
    walls, rows, ops = {}, {}, {}
    monkeypatch.setattr(stats_store, "_loaded", False)
    stats_store.load_into(walls, rows, ops)
    assert ops[("WholeStageExec", "device")] == (123456, 0.5)


def test_wholestage_records_device_wall(monkeypatch):
    from spark_rapids_tpu.plan import cost
    # under the per-query sample gate nothing is learned: a 4096-row
    # region measures dispatch floor, not per-row cost
    before = cost._OP_COSTS.get(("WholeStageExec", "device"), (0, 0.0))
    s = tpu_session()
    _chain(s.create_dataframe(_table(4096))).collect_arrow()
    assert cost._OP_COSTS.get(("WholeStageExec", "device"),
                              (0, 0.0)) == before
    # at scale (gate lowered so the test stays fast) the fused region
    # feeds its measured device wall into the learned table
    monkeypatch.setattr(cost, "_OP_COST_SAMPLE_MIN_ROWS", 1024)
    _chain(s.create_dataframe(_table(4096))).collect_arrow()
    after = cost._OP_COSTS.get(("WholeStageExec", "device"), (0, 0.0))
    assert after[0] >= before[0] + 4096
    assert after[1] > before[1]


def test_explain_prints_placement_reason():
    s = tpu_session({"spark.rapids.tpu.sql.optimizer.enabled": True})
    out = _chain(s.create_dataframe(_table(64))).explain("physical")
    assert out.startswith("placement: ")
    head = out.splitlines()[0]
    assert "host (" in head or "device (" in head


# ---------------------------------------------------------------------------
# srtpu_compile_* metrics
# ---------------------------------------------------------------------------

def test_compile_metrics_are_declared_and_recorded():
    from spark_rapids_tpu.metrics import shutdown_metrics
    from spark_rapids_tpu.metrics.registry import (MetricRegistry,
                                                   install_metrics,
                                                   metric_inventory)
    inv = metric_inventory()
    for name in ("srtpu_compile_cache_hits_total",
                 "srtpu_compile_cache_misses_total",
                 "srtpu_compile_persistent_hits_total",
                 "srtpu_compile_seconds_total"):
        assert name in inv and inv[name]["kind"] == "counter"
    reg = install_metrics(MetricRegistry())
    try:
        key = exec_cache.fused_key("metrics-" + os.urandom(4).hex(), ())
        exec_cache.get_or_build(key, lambda: (lambda: 0))
        exec_cache.get_or_build(key, lambda: (lambda: 0))
        snap = reg.snapshot()
        assert snap["srtpu_compile_cache_misses_total"]["series"][0][
            "value"] >= 1
        assert snap["srtpu_compile_cache_hits_total"]["series"][0][
            "value"] >= 1
    finally:
        shutdown_metrics()


# ---------------------------------------------------------------------------
# adhoc-jit lint rule
# ---------------------------------------------------------------------------

def _jit_findings(src, rel):
    from spark_rapids_tpu.tools.lint import AdHocJitRule
    from spark_rapids_tpu.tools.lint.framework import FileContext
    ctx = FileContext(rel, src, rel=rel)
    assert ctx.parse_error is None
    return [f for f in AdHocJitRule().check(ctx) if not ctx.suppressed(f)]


JIT_SRC = """
import functools
import jax

@jax.jit
def decorated(x):
    return x

@functools.partial(jax.jit, static_argnums=(1,))
def partial_decorated(x, n):
    return x

def builder():
    return jax.jit(lambda x: x)
"""


def test_adhoc_jit_rule_flags_unblessed_modules():
    fs = _jit_findings(JIT_SRC, "spark_rapids_tpu/exec/somewhere.py")
    assert len(fs) == 3, [repr(f) for f in fs]
    assert all(f.rule == "adhoc-jit" for f in fs)
    # keys are line-free (baseline survives unrelated edits)
    for f in fs:
        assert str(f.line) not in f.key


def test_adhoc_jit_rule_blesses_compiler_and_cache():
    for rel in ("spark_rapids_tpu/exprs/compiler.py",
                "spark_rapids_tpu/plan/exec_cache.py"):
        assert _jit_findings(JIT_SRC, rel) == []
    # files outside the package (tests, tools) are not checked
    assert _jit_findings(JIT_SRC, "tests/test_x.py") == []


def test_adhoc_jit_rule_suppression():
    src = ("import jax\n"
           "fn = jax.jit(lambda x: x)  # tpulint: disable=adhoc-jit\n")
    assert _jit_findings(src, "spark_rapids_tpu/exec/x.py") == []


def test_tree_has_no_new_adhoc_jit_findings():
    """The checked-in baseline covers every grandfathered jax.jit site;
    new ones must go through the executable cache."""
    import spark_rapids_tpu
    from spark_rapids_tpu.tools.lint import AdHocJitRule, run_lint
    from spark_rapids_tpu.tools.lint.framework import load_baseline
    pkg = os.path.dirname(spark_rapids_tpu.__file__)
    res = run_lint([pkg], rules=[AdHocJitRule()],
                   baseline=load_baseline())
    assert res.ok, [repr(f) for f in res.new]
