"""Differential window-function tests (ref window_function_test.py)."""
import pandas as pd
import pytest

from harness import assert_tpu_and_cpu_equal, tpu_session
from data_gen import DoubleGen, IntGen, gen_df
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.exprs import ColumnRef
from spark_rapids_tpu.exprs.aggregates import Average, CountStar, Max, Min, Sum
from spark_rapids_tpu.exprs.window_fns import (DenseRank, Lag, Lead, Rank,
                                               RowNumber)


def _df(s, n=512, seed=0):
    return s.create_dataframe(gen_df(
        {"p": IntGen(lo=0, hi=6, nullable=False),
         "o": IntGen(lo=0, hi=1000, nullable=False),
         "v": IntGen(lo=-100, hi=100, nullable=False)}, n=n, seed=seed))


def test_row_number():
    def q(s):
        return _df(s).with_window_column(
            "rn", RowNumber(), partition_by=["p"],
            order_by=[F.col("o").asc(), F.col("v").asc()])
    assert_tpu_and_cpu_equal(q)


def test_rank_dense_rank():
    def q(s):
        df = _df(s)
        df = df.with_window_column("rk", Rank(), partition_by=["p"],
                                   order_by=[F.col("o").asc()])
        return df.with_window_column("drk", DenseRank(), partition_by=["p"],
                                     order_by=[F.col("o").asc()])
    assert_tpu_and_cpu_equal(q)


def test_lag_lead():
    def q(s):
        df = _df(s)
        df = df.with_window_column(
            "lag1", Lag(ColumnRef("v"), 1), partition_by=["p"],
            order_by=[F.col("o").asc(), F.col("v").asc()])
        return df.with_window_column(
            "lead2", Lead(ColumnRef("v"), 2), partition_by=["p"],
            order_by=[F.col("o").asc(), F.col("v").asc()])
    assert_tpu_and_cpu_equal(q)


def test_unbounded_partition_aggs():
    def q(s):
        df = _df(s)
        df = df.with_window_column("psum", Sum(ColumnRef("v")),
                                   partition_by=["p"])
        df = df.with_window_column("pmin", Min(ColumnRef("v")),
                                   partition_by=["p"])
        df = df.with_window_column("pmax", Max(ColumnRef("v")),
                                   partition_by=["p"])
        return df.with_window_column("pcnt", CountStar(),
                                     partition_by=["p"])
    assert_tpu_and_cpu_equal(q)


def test_running_sum():
    def q(s):
        return _df(s).with_window_column(
            "rsum", Sum(ColumnRef("v")), partition_by=["p"],
            order_by=[F.col("o").asc(), F.col("v").asc()])
    assert_tpu_and_cpu_equal(q)


def test_bounded_preceding_sum():
    def q(s):
        return _df(s).with_window_column(
            "wsum", Sum(ColumnRef("v")), partition_by=["p"],
            order_by=[F.col("o").asc(), F.col("v").asc()],
            frame=("rows", -2, 0))
    assert_tpu_and_cpu_equal(q)


def test_window_no_partition():
    def q(s):
        return _df(s, n=128).with_window_column(
            "rn", RowNumber(), order_by=[F.col("o").asc(),
                                         F.col("v").asc()])
    assert_tpu_and_cpu_equal(q)
