"""Differential window-function tests (ref window_function_test.py)."""
import numpy as np
import pandas as pd
import pytest

from harness import assert_tpu_and_cpu_equal, tpu_session
from data_gen import DoubleGen, IntGen, gen_df
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.exprs import ColumnRef
from spark_rapids_tpu.exprs.aggregates import Average, CountStar, Max, Min, Sum
from spark_rapids_tpu.exprs.window_fns import (DenseRank, Lag, Lead, Rank,
                                               RowNumber)


def _df(s, n=512, seed=0):
    return s.create_dataframe(gen_df(
        {"p": IntGen(lo=0, hi=6, nullable=False),
         "o": IntGen(lo=0, hi=1000, nullable=False),
         "v": IntGen(lo=-100, hi=100, nullable=False)}, n=n, seed=seed))


def test_row_number():
    def q(s):
        return _df(s).with_window_column(
            "rn", RowNumber(), partition_by=["p"],
            order_by=[F.col("o").asc(), F.col("v").asc()])
    assert_tpu_and_cpu_equal(q)


def test_rank_dense_rank():
    def q(s):
        df = _df(s)
        df = df.with_window_column("rk", Rank(), partition_by=["p"],
                                   order_by=[F.col("o").asc()])
        return df.with_window_column("drk", DenseRank(), partition_by=["p"],
                                     order_by=[F.col("o").asc()])
    assert_tpu_and_cpu_equal(q)


def test_lag_lead():
    def q(s):
        df = _df(s)
        df = df.with_window_column(
            "lag1", Lag(ColumnRef("v"), 1), partition_by=["p"],
            order_by=[F.col("o").asc(), F.col("v").asc()])
        return df.with_window_column(
            "lead2", Lead(ColumnRef("v"), 2), partition_by=["p"],
            order_by=[F.col("o").asc(), F.col("v").asc()])
    assert_tpu_and_cpu_equal(q)


def test_unbounded_partition_aggs():
    def q(s):
        df = _df(s)
        df = df.with_window_column("psum", Sum(ColumnRef("v")),
                                   partition_by=["p"])
        df = df.with_window_column("pmin", Min(ColumnRef("v")),
                                   partition_by=["p"])
        df = df.with_window_column("pmax", Max(ColumnRef("v")),
                                   partition_by=["p"])
        return df.with_window_column("pcnt", CountStar(),
                                     partition_by=["p"])
    assert_tpu_and_cpu_equal(q)


def test_running_sum():
    def q(s):
        return _df(s).with_window_column(
            "rsum", Sum(ColumnRef("v")), partition_by=["p"],
            order_by=[F.col("o").asc(), F.col("v").asc()])
    assert_tpu_and_cpu_equal(q)


def test_bounded_preceding_sum():
    def q(s):
        return _df(s).with_window_column(
            "wsum", Sum(ColumnRef("v")), partition_by=["p"],
            order_by=[F.col("o").asc(), F.col("v").asc()],
            frame=("rows", -2, 0))
    assert_tpu_and_cpu_equal(q)


def test_window_no_partition():
    def q(s):
        return _df(s, n=128).with_window_column(
            "rn", RowNumber(), order_by=[F.col("o").asc(),
                                         F.col("v").asc()])
    assert_tpu_and_cpu_equal(q)


def test_rank_desc_multi_order_differential():
    """rank/dense_rank with DESC and multi-column orders (the host engine
    computed value-ascending ranks regardless of direction)."""
    import numpy as np
    import pyarrow as pa
    from harness import assert_tpu_and_cpu_equal
    from spark_rapids_tpu.api import functions as F

    rng = np.random.RandomState(9)
    t = pa.table({"g": pa.array(rng.choice(["a", "b"], 300)),
                  "x": pa.array(rng.randint(0, 10, 300).astype("int64")),
                  "y": pa.array(rng.randint(0, 5, 300).astype("int64"))})

    def q(s):
        df = s.create_dataframe(t)
        df = df.with_window_column(
            "r", F.rank(), partition_by=["g"],
            order_by=[F.col("x").desc(), F.col("y").asc()])
        return df.with_window_column(
            "dr", F.dense_rank(), partition_by=["g"],
            order_by=[F.col("x").desc()])
    assert_tpu_and_cpu_equal(q)


def test_window_nan_vs_null_semantics():
    """NaN is a value: it poisons frames CONTAINING it (sum/avg/max) but
    not later disjoint frames; SQL NULLs are skipped; lag/lead produce
    NULL (not NaN) outside the partition. Differential vs the host
    oracle, which computes frames independently."""
    import numpy as np
    import pyarrow as pa
    from harness import tpu_session
    rng = np.random.RandomState(9)
    n = 2000
    v = rng.rand(n)
    v[rng.rand(n) < 0.05] = np.nan
    va = pa.array(v)
    # sprinkle true NULLs too
    mask = rng.rand(n) < 0.05
    va = pa.array([None if m else x for m, x in zip(mask, v)])
    t = pa.table({"g": rng.randint(0, 20, n), "v": va, "o": rng.rand(n)})
    q = """SELECT g, sum(v) OVER (PARTITION BY g ORDER BY o
             ROWS BETWEEN 2 PRECEDING AND 1 FOLLOWING) s,
           max(v) OVER (PARTITION BY g) mx,
           lag(v, 1) OVER (PARTITION BY g ORDER BY o) lg
           FROM t ORDER BY g, o"""
    import math
    outs = []
    for en in (True, False):
        s = tpu_session({"spark.rapids.tpu.sql.enabled": en})
        s.create_dataframe(t).create_or_replace_temp_view("t")
        outs.append(s.sql(q).collect())
    for rd, rc in zip(*outs):
        for c in rd:
            a, b = rd[c], rc[c]
            if isinstance(a, float) and isinstance(b, float):
                assert (math.isnan(a) and math.isnan(b)) \
                    or abs(a - b) <= 1e-9 * (1 + abs(b)), (c, rd, rc)
            else:
                assert a == b, (c, rd, rc)


def test_window_host_sink_xla_path():
    """Terminal windows over the host-sink threshold run the same kernel
    on host XLA (no device fetch); results must match the oracle and the
    new columns must be host-resident."""
    conf = {"spark.rapids.tpu.window.hostSinkRowThreshold": 64}

    def q(s):
        return _df(s, n=512).with_window_column(
            "wsum", Sum(ColumnRef("v")), partition_by=["p"],
            order_by=[F.col("o").asc(), F.col("v").asc()],
            frame=("rows", -2, 0))
    assert_tpu_and_cpu_equal(q, conf=conf)
    # the produced window column is a HostColumn (no D2H needed)
    from harness import tpu_session
    from spark_rapids_tpu.columnar.column import HostColumn
    s = tpu_session(conf)
    df = q(s)
    physical = df._physical()
    batches = list(physical.execute(s.exec_context()))
    assert isinstance(batches[0].columns[-1], HostColumn)


def test_bounded_min_max_frames():
    """Bounded ROWS frames for MIN/MAX (r1 limitation removed; ref
    GpuBatchedBoundedWindowExec): interior sparse-table queries plus
    partition-clamped scan reads."""
    def q(s):
        df = _df(s, n=600)
        df = df.with_window_column(
            "wmin", Min(ColumnRef("v")), partition_by=["p"],
            order_by=[F.col("o").asc(), F.col("v").asc()],
            frame=("rows", -3, 0))
        df = df.with_window_column(
            "wmax", Max(ColumnRef("v")), partition_by=["p"],
            order_by=[F.col("o").asc(), F.col("v").asc()],
            frame=("rows", -2, 2))
        return df.with_window_column(
            "wmax2", Max(ColumnRef("v")), partition_by=["p"],
            order_by=[F.col("o").asc(), F.col("v").asc()],
            frame=("rows", 1, 3))
    assert_tpu_and_cpu_equal(q)


def test_bounded_min_max_half_open_frames():
    def q(s):
        df = _df(s, n=400)
        df = df.with_window_column(
            "rmin", Min(ColumnRef("v")), partition_by=["p"],
            order_by=[F.col("o").asc(), F.col("v").asc()],
            frame=("rows", None, -1))
        return df.with_window_column(
            "smax", Max(ColumnRef("v")), partition_by=["p"],
            order_by=[F.col("o").asc(), F.col("v").asc()],
            frame=("rows", 2, None))
    assert_tpu_and_cpu_equal(q)


def test_bounded_min_max_nan_and_null():
    """Spark semantics inside bounded frames: NULLs are skipped, NaN is
    greatest (poisons max; min only when the frame is all-NaN)."""
    import pyarrow as pa
    vals = [1.0, np.nan, None, 4.0, np.nan, None, 2.0, 8.0]
    t = pa.table({"g": pa.array([0] * len(vals), pa.int64()),
                  "o": pa.array(range(len(vals)), pa.int64()),
                  "v": pa.array(vals, pa.float64())})

    def q(s):
        df = s.create_dataframe(t)
        df = df.with_window_column(
            "bmin", Min(ColumnRef("v")), partition_by=["g"],
            order_by=[F.col("o").asc()], frame=("rows", -1, 0))
        return df.with_window_column(
            "bmax", Max(ColumnRef("v")), partition_by=["g"],
            order_by=[F.col("o").asc()], frame=("rows", -1, 1))
    assert_tpu_and_cpu_equal(q, ignore_order=False)


def test_window_host_numpy_path_matches_device_and_oracle():
    """The host-sink numpy fast path is a third implementation of the
    window math; pin it against BOTH the device kernel and the pandas
    oracle across fn families and frames."""
    import pyarrow as pa
    rng = np.random.RandomState(4)
    n = 900
    vals = rng.uniform(-50, 50, n)
    vmask = rng.rand(n) < 0.08
    vals[rng.rand(n) < 0.05] = np.nan
    t = pa.table({"p": pa.array(rng.randint(0, 9, n)),
                  "o": pa.array(rng.randint(0, 1000, n)),
                  "v": pa.array(np.where(vmask, 0.0, vals), mask=vmask)})

    def q(s):
        df = s.create_dataframe(t)
        df = df.with_window_column(
            "ws", Sum(ColumnRef("v")), partition_by=["p"],
            order_by=[F.col("o").asc()], frame=("rows", -2, 1))
        df = df.with_window_column(
            "wmin", Min(ColumnRef("v")), partition_by=["p"],
            order_by=[F.col("o").asc()], frame=("rows", -3, 0))
        df = df.with_window_column(
            "wmax", Max(ColumnRef("v")), partition_by=["p"],
            order_by=[F.col("o").asc()], frame=("rows", None, 2))
        df = df.with_window_column(
            "rk", F.rank(), partition_by=["p"],
            order_by=[F.col("o").desc()])
        df = df.with_window_column(
            "lg", Lag(ColumnRef("v"), 2), partition_by=["p"],
            order_by=[F.col("o").asc()])
        return df.with_window_column(
            "av", Average(ColumnRef("v")), partition_by=["p"],
            order_by=[F.col("o").asc()])

    # device path (threshold off) vs oracle
    dev = assert_tpu_and_cpu_equal(
        q, conf={"spark.rapids.tpu.window.hostSinkRowThreshold": 0},
        approximate_float=True)
    # numpy host path (threshold 1) vs oracle
    host = assert_tpu_and_cpu_equal(
        q, conf={"spark.rapids.tpu.window.hostSinkRowThreshold": 1},
        approximate_float=True)
    assert list(dev.columns) == list(host.columns)


def test_lag_explicit_default_on_device():
    """r5 review regression: the Lag/Lead default-fill must stay in the
    Lag/Lead device branch (it was briefly swallowed by a neighboring
    branch, turning lag(v, 1, default) partition heads into NULL)."""
    import pyarrow as pa
    from spark_rapids_tpu.exprs.window_fns import Lag, Lead
    from spark_rapids_tpu.exprs import ColumnRef
    t = pa.table({"p": [1, 1, 2], "o": [1, 2, 1],
                  "v": [10.0, 20.0, 30.0]})
    s = tpu_session()
    out = (s.create_dataframe(t)
           .with_window_column("lg", Lag(ColumnRef("v"), 1, -1.0),
                               partition_by=["p"],
                               order_by=[F.col("o").asc()])
           .with_window_column("ld", Lead(ColumnRef("v"), 1, -2.0),
                               partition_by=["p"],
                               order_by=[F.col("o").asc()])
           .to_pandas().sort_values(["p", "o"]))
    assert list(out["lg"]) == [-1.0, 10.0, -1.0]
    assert list(out["ld"]) == [20.0, -2.0, -2.0]
